"""Crash-safe durable documents: journal + atomic snapshots + recovery.

``DurableDocument`` wraps a document (core ``Document`` or autocommit
``AutoDoc``) with a durable write path:

* every change that enters the history — a committed transaction, a
  merge, a change absorbed from sync — is appended to an append-only
  write-ahead journal (storage/journal.py) *before* the mutating call
  returns, so an acked change is on disk (durably, under
  ``fsync="always"``);
* when the journal grows past ``compact_max_records`` /
  ``compact_max_bytes``, the full document is written to a temp file,
  fsynced, atomically renamed over the snapshot, the directory entry is
  fsynced, and only then is the journal truncated — recovery time stays
  bounded by the compaction thresholds, never by the document's age;
* ``open()`` replays snapshot + journal: the snapshot loads in salvage
  mode (a damaged one degrades instead of refusing), the journal
  truncates at the first torn record, and
  ``obs.count("journal.replayed_records" / "journal.truncated_tail")``
  report what recovery did.

The on-disk layout is a directory::

    <path>/snapshot.am    full document save (atomic-rename target)
    <path>/journal.waj    append-only change journal

Use via ``Document.open(path)`` / ``AutoDoc.open(path)``; every other
document method delegates, with the ack-point methods (commit /
apply_changes / merge / load_incremental / receive_sync_message) also
checking compaction thresholds on the way out.

Small latest-wins metadata rides in the journal as ``REC_META`` records
(re-appended after every compaction): the sync layer persists each
peer's ``shared_heads`` + epoch there (``attach_sync_session`` /
``restore_sync_session``), so a restarted durable peer resumes an
interrupted sync through the epoch/reset handshake instead of always
renegotiating from scratch.

Failure semantics: a journal append that raises leaves the in-memory
document *ahead of* disk — indistinguishable from a crash at that
instant, which is exactly the state recovery is built for.
"""

from __future__ import annotations

import contextlib
import posixpath
import sys
import threading
from typing import Dict, List, Optional

from .. import obs
from ..degrade import brownout_active
from ..integrity import DigestState, finalize_digest
from ..utils.leb128 import decode_uleb, encode_uleb
from .change import parse_change
from .journal import (
    Journal,
    OS_FS,
    REC_CHANGE,
    REC_META,
    decode_meta,
    encode_meta,
)

SNAPSHOT_NAME = "snapshot.am"
JOURNAL_NAME = "journal.waj"

_SYNC_META_PREFIX = "sync/"

# follower-side replication state rides in the journal as latest-wins
# meta (cluster/replication.py): the cursor names the leader stream and
# the last applied LSN, re-appended with every replicated batch inside
# the batch's own ack scope — so cursor and changes share one fsync and
# the cursor can never claim records the journal does not hold
REPL_META_PREFIX = "repl/"
REPL_CURSOR_KEY = REPL_META_PREFIX + "cursor"


class DocumentEvicted(Exception):
    """The durable document behind this reference was closed (demoted to
    the cold tier, or shut down) between the caller resolving the handle
    and issuing a mutation. Retrying re-resolves the handle, which
    hydrates a fresh instance — hence retriable. Without this guard a
    mutation could SILENTLY stage state on a closed instance whose
    change listener is gone: never journaled, never acked-visible,
    dropped when the instance is garbage-collected."""

    retriable = True


class DurableDocument:
    """A document whose changes survive the process. See module docstring."""

    # methods that ack durable state to a caller: wrapped so compaction
    # thresholds are checked after each (never DURING — a snapshot taken
    # mid-batch from the listener could race the op-store rebuild)
    _ACK_METHODS = frozenset(
        {"commit", "apply_changes", "merge", "load_incremental",
         "receive_sync_message"}
    )

    # host methods that mutate document state WITHOUT acking durably on
    # the spot (they stage an autocommit transaction). On a live doc they
    # delegate straight through; on a closed one they must refuse — the
    # staged ops would otherwise die with the evicted instance. Reads
    # stay allowed on a closed instance: the op-store is immutable from
    # here on, so a request that resolved the doc just before demotion
    # still answers consistently.
    _MUTATING_METHODS = frozenset({
        "put", "put_object", "insert", "insert_object", "delete",
        "increment", "splice", "splice_text", "splice_text_many",
        "mark", "unmark", "isolate", "integrate", "rollback",
    })

    def __init__(self, host, core, path, journal, *, fs,
                 compact_max_records: int, compact_max_bytes: int,
                 background_compact: bool = False,
                 compact_cost_ratio: float = 0.0):
        self._host = host  # the wrapped Document or AutoDoc
        self._core = core  # the underlying core Document
        self.path = path
        # the per-doc gauge label (doc.journal_bytes{doc=...} etc.); the
        # registry's cardinality cap bounds a many-doc server's series
        self.obs_name = posixpath.basename(path.rstrip("/")) or path
        self._fs = fs
        self._journal = journal
        self.compact_max_records = compact_max_records
        self.compact_max_bytes = compact_max_bytes
        # the per-document mutex the serving layer executes requests
        # under; the background compactor takes the same lock, so a
        # snapshot never races a mutating request
        self.lock = threading.RLock()
        # cost-based compaction gate: while the journal is smaller than
        # ``compact_cost_ratio`` x the last snapshot, skip compaction even
        # past the record threshold — re-snapshotting a large document
        # for a dribble of fresh records costs more than it saves
        # (replay stays bounded by ratio x snapshot size). 0 disables.
        self.compact_cost_ratio = compact_cost_ratio
        self._last_snapshot_bytes = 0
        # background mode (serving layer): threshold crossings schedule
        # compaction on a daemon thread instead of stalling the ack path
        self._background = background_compact
        self._compact_wake = threading.Event()
        self._compact_stop = False
        self._compact_thread: Optional[threading.Thread] = None
        self._meta: Dict[str, bytes] = {}
        self._compacting = False
        self._closed = False
        # set when a journal append failed AFTER its change entered the
        # in-memory history: memory is ahead of disk, so acking anything
        # more would strand dependents. compact() repairs (the snapshot
        # carries the full history) and clears it.
        self._broken = False
        # per-THREAD ack-scope bookkeeping (depth + whether the current
        # scope chain journaled anything). Depth is thread-local on
        # purpose: a scope's deferred boundary fsync must be paid by the
        # thread that owns the scope — were the depth shared, a scope
        # exiting while another thread's scope is still open would ack
        # with its fsync delegated to that OTHER thread, and a fault in
        # that later fsync could strike after this ack already returned
        # (the chaos suite proves exactly this). Concurrent boundary
        # fsyncs stay cheap: the journal's group-commit combiner
        # collapses them.
        self._tl_scope = threading.local()
        # read/write recency stamp (obs monotonic clock) — the tiered
        # store's LRU signal; refreshed by touch() and every ack exit
        self.last_access = obs.now()
        self._touch_exported = 0.0
        self.device_doc = None  # set by open(device=True)
        # the parsed run-coded snapshot image (storage/runsnap.py), when
        # the on-disk snapshot is ARSN: a valid prefix of the history
        # forever (history is append-only), so warm→hot promotion and the
        # next compaction rebuild their OpLog from run tables + a tail
        # append instead of re-extracting columns from every change
        self._run_image = None
        # incremental state digest (integrity.py): the XOR-of-change-
        # hashes accumulator tracks the in-memory HISTORY (fed by the
        # change listener, rebuilt on open), so two documents agree on
        # doc_digest() iff they hold the same change set + frontier —
        # the anti-entropy scrubber's comparison unit
        self._digest = DigestState()
        # cluster replication gate (cluster/replication.py): when set,
        # the OUTERMOST ack-scope exit blocks until enough followers
        # hold the batch durably — a raised gate converts the batch to
        # errors instead of acking un-replicated writes
        self.replication_gate = None

    # -- construction --------------------------------------------------------

    @classmethod
    def open(
        cls,
        path: str,
        *,
        doc_factory=None,
        actor=None,
        text_encoding=None,
        fsync: str = "always",
        fsync_interval: int = 16,
        compact_max_records: int = 1024,
        compact_max_bytes: int = 4 << 20,
        background_compact: bool = False,
        compact_cost_ratio: float = 0.0,
        device: bool = False,
        fs=None,
    ) -> "DurableDocument":
        """Open (or create) the durable document directory at ``path``.

        ``doc_factory`` picks the wrapped surface — ``AutoDoc`` (default)
        or core ``Document``. ``device=True`` additionally recovers a
        resident ``DeviceDoc``: built once from the snapshot, then warmed
        with the replayed journal changes through the incremental
        ``OpLog.append_changes`` path (``obs.span("device.recover")``).
        """
        if doc_factory is None:
            from ..api import AutoDoc

            doc_factory = AutoDoc
        fs = fs or OS_FS
        path = str(path)
        fs.makedirs(path)
        # the doc directory's OWN entry in its parent must be durable, or
        # a crash right after creation loses the whole directory no matter
        # how diligently the files inside it were fsynced
        fs.sync_dir(posixpath.dirname(path.rstrip("/")) or ".")
        host = doc_factory(actor=actor, text_encoding=text_encoding)
        core = host.doc if hasattr(host, "doc") else host

        with obs.span("durable.open"):
            # the journal's lock comes FIRST: reading the snapshot before
            # holding it could pair an old snapshot with a journal another
            # process compacted in between, silently losing acked changes
            journal, records, tail = Journal.open(
                posixpath.join(path, JOURNAL_NAME),
                fs=fs, fsync=fsync, fsync_interval=fsync_interval,
            )
            try:
                return cls._recover(
                    host, core, path, journal, records, fs=fs, device=device,
                    compact_max_records=compact_max_records,
                    compact_max_bytes=compact_max_bytes,
                    background_compact=background_compact,
                    compact_cost_ratio=compact_cost_ratio,
                )
            except Exception:
                journal.close()  # release the flock; don't wedge the dir
                raise

    @classmethod
    def _recover(cls, host, core, path, journal, records, *, fs, device,
                 compact_max_records, compact_max_bytes,
                 background_compact=False,
                 compact_cost_ratio=0.0) -> "DurableDocument":
        """Snapshot load + journal replay, under the already-held lock."""
        from . import runsnap

        snap_path = posixpath.join(path, SNAPSHOT_NAME)
        snap_bytes = 0
        run_image = None
        if fs.exists(snap_path):
            snap = fs.read_bytes(snap_path)
            snap_bytes = len(snap)
            if runsnap.is_runsnap(snap):
                try:
                    run_image = runsnap.parse(snap)
                    core.apply_changes(run_image.changes)
                except runsnap.RunSnapError:
                    # corrupt ARSN container: the embedded change chunks
                    # are magic-prefixed, so the legacy salvage scan
                    # carves whatever survives — same degradation as a
                    # damaged chunk snapshot
                    run_image = None
                    core.load_incremental(snap, on_partial="salvage")
            else:
                core.load_incremental(snap, on_partial="salvage")
            obs.count(
                "store.hydrate_bytes", n=snap_bytes,
                labels={"codec": "runsnap" if run_image is not None else "chunk"},
            )
        if run_image is not None and run_image.n_changes != len(core.history):
            # partial apply (causally incomplete container): the image no
            # longer names a history prefix, drop it
            run_image = None
        dev = None
        if device:
            from ..ops.device_doc import DeviceDoc
            from ..ops.oplog import OpLog

            # an empty history still gets a resident DeviceDoc: a fresh
            # device-mode doc starts tracking from its first sync feed
            with obs.span("device.recover", phase="snapshot"):
                log = None
                if run_image is not None:
                    try:
                        log = run_image.to_oplog(
                            [a.stored for a in core.history]
                        )
                    except Exception:
                        log = None
                if log is None:
                    if core.history:
                        obs.count("oplog.hydrate_reencode")
                    log = OpLog.from_changes(
                        [a.stored for a in core.history]
                    )
                dev = DeviceDoc.resolve(log)
        meta: Dict[str, bytes] = {}
        replayed: List = []
        for rec in records:
            if rec.rec_type == REC_CHANGE:
                try:
                    change, _ = parse_change(rec.payload)
                except Exception:
                    # CRC-valid record with an unparseable chunk body:
                    # treat like a salvage drop, keep replaying
                    obs.count("journal.rejected_records")
                    continue
                replayed.append(change)
            elif rec.rec_type == REC_META:
                name, blob = decode_meta(rec.payload)
                meta[name] = blob
        obs.count("journal.replayed_records", n=len(replayed))
        if replayed:
            core.apply_changes(replayed)
            if device:
                from ..ops.device_doc import DeviceDoc
                from ..ops.oplog import OpLog

                with obs.span("device.recover", changes=len(replayed)):
                    if dev is None:
                        dev = DeviceDoc.resolve(OpLog.from_changes(replayed))
                    else:
                        dev.apply_changes(replayed)

        dd = cls(
            host, core, path, journal, fs=fs,
            compact_max_records=compact_max_records,
            compact_max_bytes=compact_max_bytes,
            background_compact=background_compact,
            compact_cost_ratio=compact_cost_ratio,
        )
        dd._meta = meta
        dd.device_doc = dev
        if dev is not None:
            # the resident mirror exports doc.resident_ops /
            # doc.device_bytes under the same per-doc label
            dev.obs_name = dd.obs_name
            dev._export_doc_gauges()
        dd._last_snapshot_bytes = snap_bytes
        dd._run_image = run_image
        # full digest rebuild, once per open — every later change folds
        # in incrementally through the listener below
        dd._digest.recompute(a.stored.hash for a in core.history)
        core.change_listeners.append(dd._on_change)
        dd._export_doc_gauges()
        return dd

    # -- delegation ----------------------------------------------------------

    def __getattr__(self, name):
        # only reached for names this wrapper does not define itself
        attr = getattr(object.__getattribute__(self, "_host"), name)
        if name in DurableDocument._ACK_METHODS and callable(attr):
            # the doc lock excludes the background compactor's snapshot
            # from racing a commit/merge/sync apply; uncontended RLock
            # cost on the single-threaded path is negligible
            def _acked(*a, _attr=attr, **kw):
                if self._closed:
                    raise DocumentEvicted(
                        f"durable document {self.obs_name!r} was demoted "
                        "to cold; retry to reopen"
                    )
                # ack scope OUTSIDE the lock (the same shape the serving
                # layer's batch drain uses): the boundary fsync and the
                # replication ack gate then run lock-free, so a follower
                # snapshot catch-up needing this lock can proceed while
                # a gated commit waits for it
                with self.ack_scope():
                    with self.lock:
                        return _attr(*a, **kw)

            # bound host methods are stable for this instance's lifetime:
            # memoize the wrapper so hot-path calls (commit per edit) skip
            # the __getattr__ fallback + closure rebuild from now on
            self.__dict__[name] = _acked
            return _acked
        if name in DurableDocument._MUTATING_METHODS and callable(attr):
            def _guarded(*a, _attr=attr, **kw):
                if self._closed:
                    raise DocumentEvicted(
                        f"durable document {self.obs_name!r} was demoted "
                        "to cold; retry to reopen"
                    )
                return _attr(*a, **kw)

            self.__dict__[name] = _guarded
            return _guarded
        return attr

    @property
    def _ack_depth(self) -> int:
        """Depth of the CURRENT THREAD's ack-scope chain (0 = outside)."""
        return getattr(self._tl_scope, "depth", 0)

    @contextlib.contextmanager
    def ack_scope(self):
        """Context manager marking one ack boundary: per-change fsyncs
        inside it are deferred to a single policy fsync (plus a compaction
        check) on exit — even on error, whatever DID enter history must be
        durable at ack. The sync session wraps each received message in
        this when the document is durable."""
        tl = self._tl_scope
        tl.depth = getattr(tl, "depth", 0) + 1
        if tl.depth == 1:
            tl.appended = False
        try:
            yield
        finally:
            tl.depth -= 1
            # a double fault in append() can poison the journal closed
            # while the original I/O error is still unwinding — syncing
            # then would only mask it with 'journal is closed'.
            # Nested scopes defer to the OUTERMOST exit ON THIS THREAD:
            # the serving layer wraps a whole drained batch of wrapped
            # ack calls in one scope, and that group pays one fsync
            # (group commit)
            if tl.depth == 0 and not self._journal.closed:
                self._journal.policy_sync()
                if self.replication_gate is not None:
                    # quorum before ack: the ack_replicas contract
                    # ("on K+1 disks when acked") overrides a lazier
                    # fsync policy — force local durability so the
                    # gate's target covers this batch, then wait for
                    # the follower copies the contract promises
                    self._journal.sync()
                    self.replication_gate()
                self.maybe_compact()
                self._export_doc_gauges()
            elif (
                tl.depth == 0
                and self._journal.poisoned
                and getattr(tl, "appended", False)
                and sys.exc_info()[0] is None
            ):
                # ANOTHER thread's failed fsync poisoned the journal
                # while this scope's appends were pending: they can
                # never be made durable, so exiting cleanly here would
                # ack un-fsynced writes. Every covered waiter errors —
                # unless an exception is already unwinding (masking the
                # original fault helps nobody). A scope that journaled
                # nothing (a read batch on the degraded doc) still
                # serves.
                raise self._journal._closed_error()

    def _export_doc_gauges(self) -> None:
        """Per-doc accounting at the ack boundary: journal footprint and
        a last-access stamp (seconds on the obs monotonic clock — age =
        ``obs.now() - value``). These are the residency-admission signals
        the tiered store's policy consumes; the device layer exports
        ``doc.resident_ops`` / ``doc.device_bytes`` alongside."""
        self.last_access = self._touch_exported = obs.now()
        labels = {"doc": self.obs_name}
        obs.gauge_set("doc.journal_bytes", self._journal.size_bytes,
                      labels=labels)
        obs.gauge_set("doc.last_access_seconds", self.last_access,
                      labels=labels)
        obs.gauge_set("doc.digest_changes", self._digest.count,
                      labels=labels)

    # touch() refreshes the exported gauge at most this often: the stamp
    # the eviction policy reads is the plain attribute (free), and a
    # registry-lock + flight-ring write per REQUEST would make every
    # shard thread serialize on two process-global locks
    TOUCH_EXPORT_INTERVAL_S = 1.0

    def touch(self) -> None:
        """Refresh the last-access stamp from the READ path. The write
        path refreshes at every ack-scope exit, but a read-hot document
        that never commits would otherwise look idle to the tiered
        store's LRU policy and be demoted out from under its readers —
        the RPC layer calls this on every document access. The policy
        reads ``self.last_access`` directly, so the hot path is one
        clock read + one attribute store; the scrape-visible gauge
        refreshes at a bounded (1s) cadence."""
        now = obs.now()
        self.last_access = now
        if now - self._touch_exported >= self.TOUCH_EXPORT_INTERVAL_S:
            self._touch_exported = now
            obs.gauge_set("doc.last_access_seconds", now,
                          labels={"doc": self.obs_name})

    # -- device-mirror residency (tiered store hot <-> warm) -----------------

    def drop_device_mirror(self):
        """Demote hot -> warm: release the resident ``DeviceDoc`` (and
        its per-doc device gauges) while the host op-store keeps
        serving. Returns the dropped mirror (for callers that need to
        detach it from live sessions) or None."""
        dev = self.device_doc
        self.device_doc = None
        if dev is not None:
            obs.remove_doc_gauges(self.obs_name, device_only=True)
            # retain the run-coded column image of the dropped mirror: the
            # next warm→hot promotion (or compaction) rebuilds from run
            # tables instead of re-extracting every change — zero-encode
            # residency transitions even before any compact() has written
            # an ARSN snapshot
            from . import runsnap

            if runsnap.enabled():
                try:
                    idx = self._core.history_index
                    if len(dev.log.changes) == len(self._core.history) and all(
                        c.hash in idx for c in dev.log.changes
                    ):
                        self._run_image = runsnap.RunImage.from_log(dev.log)
                except Exception:
                    pass
        return dev

    def build_device_mirror(self):
        """Promote warm -> hot: build a resident ``DeviceDoc`` from the
        committed history (the same construction ``open(device=True)``
        performs). No-op when a mirror already exists."""
        if self.device_doc is not None:
            return self.device_doc
        from ..ops.device_doc import DeviceDoc
        from ..ops.oplog import OpLog

        with self.lock:
            with obs.span("device.recover", phase="promote"):
                hist = [a.stored for a in self._core.history]
                # the retained run image makes promotion decode-only:
                # run tables expand (np.repeat) and the journal tail
                # splices in — no per-change column re-extraction
                log = self._image_log(hist)
                if log is None:
                    if hist:
                        obs.count("oplog.hydrate_reencode")
                    log = OpLog.from_changes(hist)
                dev = DeviceDoc.resolve(log)
            dev.obs_name = self.obs_name
            self.device_doc = dev
            dev._export_doc_gauges()
            # the promotion shipped the compressed image (the resolve's
            # H2D staging moves run tables, merge.stage_cols_device);
            # record what warm->hot residency actually costs
            obs.count("store.promote_resident_bytes",
                      n=dev.resident_nbytes())
        return dev

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- the durable write path ----------------------------------------------

    def _on_change(self, stored) -> None:
        """Change listener (core/document.py ``_update_history``): journal
        every change the moment it enters history, before the mutating
        call acks to its caller."""
        from .journal import JournalPoisoned

        # the digest mirrors HISTORY, and this listener fires exactly
        # once per change entering it — fold the hash in before any
        # journaling outcome, so memory and digest never drift even on
        # the broken (memory-ahead-of-disk) paths below
        self._digest.add(stored.hash)
        if self._broken:
            # refusing BEFORE the append keeps every later change un-acked
            # while memory is ahead of disk — no silently stranded deps.
            # JournalPoisoned is retriable: the doc is degraded (read-only)
            # until a compaction or reopen restores it, and in a cluster a
            # failover can restore service before that
            raise JournalPoisoned(
                "durable document degraded: out of sync with its journal "
                "after a failed append; compact() or reopen to recover"
            )
        raw = stored.raw_bytes
        if raw is None:
            from ..errors import AutomergeError

            # the change is already in history: memory is ahead of disk
            # exactly as in the append-failure case below
            self._broken = True
            raise AutomergeError(
                "durable document received a change without raw bytes"
            )
        # inside a wrapped ack call the fsync is deferred to its boundary;
        # an unwrapped path (e.g. a manual Transaction.commit) syncs here
        try:
            self._journal.append(
                REC_CHANGE, raw, auto_sync=self._ack_depth == 0
            )
            self._tl_scope.appended = True
        except Exception:
            # the change is already in history (listeners fire after the
            # bookkeeping): memory is now ahead of disk. Poison until a
            # compaction re-establishes disk >= memory.
            self._broken = True
            raise

    def doc_digest(self) -> Dict[str, object]:
        """The verifiable state digest: accumulator + change count +
        sorted heads under one SHA-256 (integrity.finalize_digest).
        Taken under the doc lock so heads and accumulator describe one
        instant."""
        with self.lock:
            heads = self._core.get_heads()
            acc, count = self._digest.value()
        return {"digest": finalize_digest(acc, count, heads),
                "changes": count}

    @property
    def journal(self) -> Journal:
        return self._journal

    @property
    def degraded(self) -> bool:
        """True while this document cannot ack writes: a journal append
        failed after its change entered history (memory ahead of disk),
        or a failed fsync poisoned the journal outright. Reads still
        serve; mutations raise the retriable ``JournalPoisoned`` until
        ``compact()`` (fresh snapshot re-establishes disk >= memory,
        reviving a poisoned journal) or a reopen recovers."""
        return self._broken or self._journal.poisoned

    @property
    def meta(self) -> Dict[str, bytes]:
        """Latest-wins journal metadata (read the dict, write via set_meta)."""
        return dict(self._meta)

    def set_meta(self, name: str, blob: bytes) -> None:
        self._meta[name] = blob
        # inside an ack scope (e.g. sync-session persistence riding a
        # received message) the record joins the boundary's single fsync
        self._journal.append(
            REC_META, encode_meta(name, blob), auto_sync=self._ack_depth == 0
        )
        self._tl_scope.appended = True

    def sync(self) -> None:
        """Force-fsync the journal regardless of policy."""
        self._journal.sync()

    def close(self) -> None:
        if self._closed:
            return
        # retire the background compactor first: a compaction racing the
        # final commit/close would truncate a journal close() is flushing
        if self._compact_thread is not None:
            self._compact_stop = True
            self._compact_wake.set()
            self._compact_thread.join(timeout=30)
            self._compact_thread = None
        # an AutoDoc host may hold a pending autocommit transaction; every
        # other exit surface (save / sync) auto-commits it, so close must
        # too — silently dropping acked-looking edits would betray the
        # whole layer. (A live MANUAL transaction stays the caller's
        # responsibility, as everywhere else.)
        try:
            commit = getattr(self._host, "commit", None)
            # a degraded doc cannot journal the commit anyway — raising
            # out of close() would only block the reopen that repairs it
            if callable(commit) and not self.degraded:
                commit()  # journals through the listener; close syncs below
        finally:
            # even if that last commit fails, the journal handle (and its
            # flock) must be released or the document is wedged for the
            # life of the process
            self._closed = True
            try:
                self._core.change_listeners.remove(self._on_change)
            except ValueError:
                pass
            self._journal.close()
            # per-doc gauge hygiene: a closed document's label sets must
            # not occupy the registry's cardinality cap forever (at
            # store scale that would collapse every later document's
            # admission signal into {overflow=true})
            obs.remove_doc_gauges(self.obs_name)

    # -- compaction ----------------------------------------------------------

    def maybe_compact(self) -> bool:
        """Compact iff the journal crossed a threshold (and, when a cost
        ratio is set, the journal is worth the snapshot's cost). Called
        after every ack-point method; cheap when below threshold. In
        background mode the actual compaction runs on a daemon thread
        under this document's lock, so it never stalls the ack path."""
        j = self._journal
        if j.closed:
            # a poisoned journal never auto-compacts: recovery from a
            # disk fault is an EXPLICIT compact()/reopen (the fault may
            # still be live — ENOSPC does not clear itself)
            return False
        if (
            j.record_count <= self.compact_max_records
            and j.size_bytes <= self.compact_max_bytes
        ):
            return False
        if (
            self.compact_cost_ratio > 0.0
            and j.size_bytes < self.compact_cost_ratio * self._last_snapshot_bytes
        ):
            obs.count("compact.deferred_by_cost")
            return False
        if brownout_active():
            # brownout: background compaction is exactly the churn a
            # degraded node defers — the journal keeps growing (bounded
            # by disk, not RSS) and compacts once pressure lifts
            obs.count("compact.deferred_brownout")
            return False
        if self._background:
            self._schedule_compact()
            return False
        return self.compact()

    def _schedule_compact(self) -> None:
        if self._compact_thread is None:
            self._compact_thread = threading.Thread(
                target=self._compact_loop,
                name=f"compact:{self.path}",
                daemon=True,
            )
            self._compact_thread.start()
        self._compact_wake.set()

    def _compact_loop(self) -> None:
        while True:
            self._compact_wake.wait()
            self._compact_wake.clear()
            if self._compact_stop:
                return
            try:
                # timed acquire, re-checking the stop flag: close() may be
                # invoked by a thread that already HOLDS the doc lock (the
                # serving worker executing a `free`), and its join() would
                # otherwise wait out the full timeout against us blocking
                # on that very lock
                while not self.lock.acquire(timeout=0.05):
                    if self._compact_stop:
                        return
                try:
                    if not self._closed:
                        self.compact()
                finally:
                    self.lock.release()
            except Exception as e:  # noqa: BLE001 — background must not die
                obs.count("compact.background_error", error=str(e)[:200])

    def _image_log(self, hist):
        """An OpLog covering ``hist`` rebuilt from the retained run image
        (decode + tail append — zero re-encode of covered changes), or
        None when no image applies."""
        img = self._run_image
        if img is None or img.n_changes > len(hist):
            return None
        try:
            hset = set(img.change_hashes())
            idx = self._core.history_index
            if len(hset) != img.n_changes or not all(h in idx for h in hset):
                return None
            log = img.to_oplog()
            tail = [c for c in hist if c.hash not in hset]
            if len(tail) != len(hist) - img.n_changes:
                return None
            if tail and log.append_changes(tail) is None:
                return None
            return log
        except Exception:
            return None

    def _snapshot_log(self):
        """An OpLog of exactly the committed history, preferring sources
        that already hold the run-coded columns: the resident device
        mirror, then the retained snapshot image plus a journal-tail
        append (the incremental merge — only the fresh changes are
        extracted and spliced), and only as a last resort a full
        ``from_changes`` rebuild (counted: ``compact.image_rebuild``)."""
        hist = [a.stored for a in self._core.history]
        dev = self.device_doc
        if dev is not None:
            try:
                idx = self._core.history_index
                if len(dev.log.changes) == len(hist) and all(
                    c.hash in idx for c in dev.log.changes
                ):
                    return dev.log
            except Exception:
                pass
        log = self._image_log(hist)
        if log is not None:
            return log
        from ..ops.oplog import OpLog

        obs.count("compact.image_rebuild")
        return OpLog.from_changes(hist)

    def _build_snapshot(self):
        """The snapshot file bytes for the current committed history:
        ``(data, image)`` where ``image`` is the parsed run-coded image
        (retained for future hydrations), or ``(legacy bytes, None)``
        when run-coded persistence is disabled or inapplicable."""
        from . import runsnap

        if runsnap.enabled():
            try:
                log = self._snapshot_log()
                data = runsnap.encode_snapshot(log, self._core.get_heads())
                return data, runsnap.parse(data)
            except runsnap.RunSnapError:
                obs.count("compact.runsnap_fallback")
        return self._core.save(), None

    def snapshot_bytes(self) -> bytes:
        """The full-history snapshot in the on-disk codec — the same
        bytes ``compact()`` would write, shipped verbatim by replication
        catch-up (``replSnapshot``/``replReset``) and cold migration so
        the receiver hydrates without a re-encode on either end."""
        with self.lock:
            data, image = self._build_snapshot()
            if image is not None:
                self._run_image = image
            return data

    def compact(self) -> bool:
        """Snapshot-then-truncate: write the full save to a temp file,
        fsync it, atomically rename over the snapshot, fsync the
        directory entry, then truncate the journal (metadata records are
        re-appended so they survive). Every step durable before the next
        — the orderings the crash suite proves are exactly these.

        The snapshot is the run-coded image (storage/runsnap.py) unless
        ``AUTOMERGE_TPU_RUNSNAP=0``; successive compactions merge only
        the journal tail into the retained image (incremental, column-
        by-column) instead of re-extracting the whole history, and the
        ``maybe_compact`` cost gate (``compact_cost_ratio``) bounds
        write amplification: ``compact.bytes_written`` vs
        ``compact.tail_bytes_retired`` is the model's measured ratio."""
        with self.lock:
            if (
                self._compacting
                or self._closed
                or (self._journal.closed and not self._journal.poisoned)
            ):
                return False
            live = self._core._live_transaction()
            if live is not None and live.pending_ops():
                return False  # mid-manual-transaction: defer to the next ack
            self._compacting = True
            try:
                with obs.span("compact.total"):
                    # snapshot the CORE: the journal holds exactly the
                    # committed history, so that is what the snapshot
                    # must cover — and a background compaction must not
                    # side-effect-commit a half-built autocommit tx out
                    # from under a mutating thread (host.save() would)
                    tail_bytes = self._journal.size_bytes
                    data, image = self._build_snapshot()
                    snap = posixpath.join(self.path, SNAPSHOT_NAME)
                    tmp = snap + ".tmp"
                    with obs.span("compact.snapshot", bytes=len(data)):
                        f = self._fs.open(tmp, "wb")
                        try:
                            f.write(data)
                            self._fs.fsync(f)
                        finally:
                            f.close()
                        self._fs.replace(tmp, snap)
                        self._fs.sync_dir(self.path)
                    with obs.span("compact.truncate"):
                        if self._journal.poisoned:
                            # the snapshot above covers the FULL history,
                            # so the unknowable on-disk journal tail can
                            # be discarded: re-acquire the file + flock
                            # as an empty journal (hooks survive)
                            self._journal.revive()
                        else:
                            self._journal.truncate()
                        for name, blob in self._meta.items():
                            self._journal.append(
                                REC_META, encode_meta(name, blob),
                                auto_sync=False,
                            )
                        self._journal.sync()
                obs.count("compact.runs")
                # write-amplification accounting: bytes rewritten vs the
                # journal tail this compaction retired — the cost model's
                # two sides, summable across a run
                obs.count("compact.bytes_written", n=len(data))
                obs.count("compact.tail_bytes_retired", n=tail_bytes)
                if image is not None:
                    self._run_image = image
                self._last_snapshot_bytes = len(data)
                # the snapshot carries the FULL in-memory history, so disk
                # is caught up even if a journal append had failed earlier
                self._broken = False
                # a background compaction shrinks the journal outside any
                # ack scope: refresh the footprint gauge here too
                obs.gauge_set("doc.journal_bytes", self._journal.size_bytes,
                              labels={"doc": self.obs_name})
                return True
            finally:
                self._compacting = False

    # -- replication (cluster/replication.py rides these) --------------------

    @property
    def replication_cursor(self) -> Optional[bytes]:
        """The persisted follower cursor blob (None when this document
        has never followed a leader, or was promoted and compacted)."""
        return self._meta.get(REPL_CURSOR_KEY)

    def acked_prefix(self) -> tuple:
        """(acked, appended) journal seqs: every append <= acked is
        durable on this node's disk — the prefix replication ships and
        promotion compares."""
        j = self._journal
        return j.acked_seq, j.append_seq

    def apply_replicated(self, records, cursor: Optional[bytes],
                         *, device_feed=None) -> int:
        """Apply a batch of shipped journal records through the normal
        listener path: changes enter history (journaled locally before
        ack, deduplicated by hash exactly like a re-delivered sync
        frame), replicated meta overwrites latest-wins (so a peer's
        ``sync/<peer>`` shared_heads survive failover), and the cursor
        meta joins the SAME ack scope — one fsync covers the whole batch
        and the cursor is durable iff the records are.

        ``device_feed(doc, dev, changes)``: when given and a resident
        device mirror exists, the applied changes are handed to it AFTER
        the durable apply — the cluster node's batched follower drain
        collects every drained document's feed into one vectorized
        cross-doc staging pass (ops/host_batch.py) so the mirror keeps
        up at super-batch speed. Without the hook the mirror is left
        alone (the pre-existing serial behavior)."""
        from .change import parse_change

        changes = []
        metas = []
        for rec_type, payload in records:
            if rec_type == REC_CHANGE:
                try:
                    change, _ = parse_change(payload)
                except Exception:
                    # CRC-framed but unparseable — mirror recovery: count
                    # and keep the stream moving (the leader journaled it,
                    # so a reject here is a codec bug, not data loss)
                    obs.count("journal.rejected_records")
                    continue
                changes.append(change)
            elif rec_type == REC_META:
                name, blob = decode_meta(payload)
                if name.startswith(REPL_META_PREFIX):
                    continue  # never adopt another node's own cursor
                metas.append((name, blob))
        with self.lock, self.ack_scope():
            if changes:
                # through the wrapper: the change listener journals each
                # applied change, duplicates drop on the history index
                self.apply_changes(changes)
            for name, blob in metas:
                self.set_meta(name, blob)
            if cursor is not None:
                self.set_meta(REPL_CURSOR_KEY, cursor)
        if changes and device_feed is not None:
            dev = self.device_doc
            if dev is not None:
                device_feed(self, dev, changes)
        return len(changes)

    def apply_replicated_snapshot(self, data: bytes,
                                  cursor: Optional[bytes]) -> None:
        """Catch-up path for a new or lagging follower: load a full
        leader snapshot (known changes deduplicate on the history index,
        so re-snapshotting after failover converges instead of erroring)
        and persist the new cursor under the same ack scope.

        A run-coded (ARSN) snapshot applies through its verbatim change
        chunks — the same bytes the leader's disk holds — and, when this
        follower was empty, the decoded image is adopted so the follower's
        own hydrations and compactions start run-coded too. Corruption
        raises (``on_partial="error"`` semantics: a shipped snapshot is
        never silently partial)."""
        from . import runsnap

        with self.lock, self.ack_scope():
            if runsnap.is_runsnap(data):
                image = runsnap.parse(data)  # RunSnapError on corruption
                was_empty = not self._core.history
                self.apply_changes(image.changes)
                obs.count("store.hydrate_bytes", n=len(data),
                          labels={"codec": "runsnap"})
                if was_empty and len(self._core.history) == image.n_changes:
                    self._run_image = image
            else:
                obs.count("store.hydrate_bytes", n=len(data),
                          labels={"codec": "chunk"})
                self.load_incremental(data, on_partial="error")
            if cursor is not None:
                self.set_meta(REPL_CURSOR_KEY, cursor)

    # -- sync-session persistence (shared_heads survive restarts) ------------

    @staticmethod
    def _sync_key(peer: str) -> str:
        return _SYNC_META_PREFIX + peer

    def attach_sync_session(self, peer: str, session):
        """Persist ``session``'s shared_heads (plus its epoch) under
        ``peer`` whenever they change; returns the session."""
        key = self._sync_key(peer)

        def _persist(encoded: bytes, _sess=session) -> None:
            body = bytearray()
            encode_uleb(_sess.epoch, body)
            body += encoded
            self.set_meta(key, bytes(body))

        session.persist = _persist
        return session

    def restore_sync_session(self, peer: str, *, config=None):
        """Rebuild the sync session for ``peer`` after a restart: the
        persisted shared_heads seed the state and the epoch is bumped so
        the surviving peer runs the epoch/reset handshake instead of a
        full resync. A peer never seen before gets a fresh session."""
        from ..sync.session import SyncSession

        blob = self._meta.get(self._sync_key(peer))
        # the session drives the WRAPPER (self): receives and commits hit
        # the ack path, so batches fsync once and compaction keeps
        # happening mid-sync
        if blob is None:
            sess = SyncSession(self, epoch=1, config=config,
                               device_doc=self.device_doc)
        else:
            epoch, pos = decode_uleb(blob, 0)
            sess = SyncSession.restore(
                self, bytes(blob[pos:]), epoch=epoch + 1, config=config
            )
            sess.device_doc = self.device_doc
        self.attach_sync_session(peer, sess)
        # persist the bumped epoch NOW: a second crash-restart with no
        # sync progress in between must still present a fresh epoch, or
        # the survivor's dup suppression eats the new incarnation's frames
        sess._maybe_persist()
        return sess
