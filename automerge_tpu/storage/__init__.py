"""Storage layer: chunk framing, change/document columnar codecs, the
append-only change journal, and the crash-safe durable document wrapper.

Submodules import lazily so the hot paths (chunk/change) never pay for
the durability machinery they don't use.
"""

__all__ = ["DurableDocument", "Journal", "SimFS", "CrashPoint"]


def __getattr__(name):
    if name == "DurableDocument":
        from .durable import DurableDocument

        return DurableDocument
    if name == "Journal":
        from .journal import Journal

        return Journal
    if name in ("SimFS", "CrashPoint"):
        from . import crashsim

        return getattr(crashsim, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
