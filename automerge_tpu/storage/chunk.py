"""Chunk framing: magic bytes, checksum, type byte, length prefix.

Byte-compatible with the reference (reference:
rust/automerge/src/storage/chunk.rs, storage.rs MAGIC_BYTES). A chunk is:

    magic (4 bytes: 85 6f 4a 83)
    checksum (4 bytes: first 4 bytes of the chunk hash)
    chunk type (1 byte: 0=document, 1=change, 2=compressed change)
    data length (ULEB128)
    data

The chunk hash — which doubles as the change hash for change chunks — is
SHA-256 over (type byte || ULEB(len) || data).
"""

from __future__ import annotations

import hashlib
import zlib
from typing import Iterator, NamedTuple

from ..utils.leb128 import LEBDecodeError, decode_uleb, encode_uleb

MAGIC_BYTES = bytes([0x85, 0x6F, 0x4A, 0x83])

CHUNK_DOCUMENT = 0
CHUNK_CHANGE = 1
CHUNK_COMPRESSED = 2

DEFLATE_MIN_SIZE = 256  # reference: storage/change.rs DEFLATE_MIN_SIZE


from ..errors import AutomergeError


class ChunkParseError(AutomergeError):
    pass


def chunk_hash(chunk_type: int, data: bytes) -> bytes:
    body = bytearray([chunk_type])
    encode_uleb(len(data), body)
    body += data
    return hashlib.sha256(bytes(body)).digest()


class RawChunk(NamedTuple):
    chunk_type: int
    checksum: bytes  # 4 bytes as stored
    hash: bytes  # 32-byte SHA-256 of (type || len || data)
    data: bytes
    offset: int = -1  # position in the scanned buffer (scan_chunks sets it)

    @property
    def checksum_valid(self) -> bool:
        return self.hash[:4] == self.checksum


def write_chunk(chunk_type: int, data: bytes) -> bytes:
    h = chunk_hash(chunk_type, data)
    out = bytearray(MAGIC_BYTES)
    out += h[:4]
    out.append(chunk_type)
    encode_uleb(len(data), out)
    out += data
    return bytes(out)


def parse_chunk(buf: bytes, pos: int = 0) -> tuple[RawChunk, int]:
    """Parse one chunk starting at ``pos``; returns (chunk, new_pos).

    Compressed change chunks are inflated transparently: the returned chunk is
    the equivalent uncompressed change chunk (its stored checksum is the
    original's, which the reference derives from the *uncompressed* data).
    """
    # header = magic(4) + checksum(4) + type(1): 9 bytes before the length
    # field, so an 8-byte-exact input is still truncated
    if pos + 9 > len(buf):
        raise ChunkParseError("truncated chunk header")
    if buf[pos : pos + 4] != MAGIC_BYTES:
        raise ChunkParseError("invalid magic bytes")
    checksum = bytes(buf[pos + 4 : pos + 8])
    chunk_type = buf[pos + 8]
    if chunk_type > CHUNK_COMPRESSED:
        raise ChunkParseError(f"unknown chunk type {chunk_type}")
    try:
        length, data_start = decode_uleb(buf, pos + 9)
    except LEBDecodeError as e:
        raise ChunkParseError(
            f"chunk length field at byte {pos + 9} runs past end of input: {e}"
        ) from e
    data_end = data_start + length
    if data_end > len(buf):
        raise ChunkParseError("chunk data extends past end of input")
    data = bytes(buf[data_start:data_end])
    if chunk_type == CHUNK_COMPRESSED:
        try:
            data = zlib.decompress(data, wbits=-15)  # raw DEFLATE stream
        except zlib.error as e:
            raise ChunkParseError(f"invalid deflate stream: {e}") from e
        chunk_type = CHUNK_CHANGE
    h = chunk_hash(chunk_type, data)
    return RawChunk(chunk_type, checksum, h, data), data_end


def iter_chunks(buf: bytes) -> Iterator[RawChunk]:
    pos = 0
    while pos < len(buf):
        chunk, pos = parse_chunk(buf, pos)
        yield chunk


class DroppedRegion(NamedTuple):
    """A byte range skipped by ``scan_chunks``: [offset, end) plus why."""

    offset: int
    end: int
    reason: str
    checksum: bytes  # stored checksum when the header was readable, else b""
    hash: bytes  # computed hash when the chunk parsed at all, else b""


def scan_chunks(buf: bytes) -> Iterator["RawChunk | DroppedRegion"]:
    """Fault-tolerant chunk walk: yield every verifiable chunk and a
    ``DroppedRegion`` for every corrupt span.

    Unlike ``iter_chunks`` this never raises on malformed input: a chunk
    that fails to parse or whose checksum does not match is reported as
    dropped, and the scan resynchronises at the next ``MAGIC_BYTES``
    occurrence (trusting the corrupt chunk's own length field only when
    it lands exactly on another magic marker or end-of-input).

    Carving caveat: resynchronisation cannot tell a real chunk boundary
    from chunk-shaped bytes *inside* a corrupt span — e.g. a save stored
    as a bytes scalar within the damaged chunk. Chunks recovered after a
    ``DroppedRegion`` may therefore originate from embedded data; every
    resync point is visible as that region's ``end``, so callers needing
    certainty can treat post-resync chunks as suspect.
    """
    pos = 0
    n = len(buf)
    while pos < n:
        chunk = None
        end = None
        reason = ""
        try:
            chunk, end = parse_chunk(buf, pos)
        except Exception as e:  # any decode error, incl. nested LEB/zlib
            reason = str(e) or type(e).__name__
        if chunk is not None and chunk.checksum_valid:
            yield chunk._replace(offset=pos)
            pos = end
            continue
        # corrupt span: decide where to resume. Only a span that actually
        # starts with magic bytes has a readable checksum field — anything
        # else would present arbitrary garbage as a chunk identity.
        header_readable = (
            pos + 8 <= n and bytes(buf[pos : pos + 4]) == MAGIC_BYTES
        )
        checksum = bytes(buf[pos + 4 : pos + 8]) if header_readable else b""
        if chunk is not None:
            reason = "checksum mismatch"
            if end == n or buf[end : end + 4] == MAGIC_BYTES:
                resume = end  # length field still framed the chunk correctly
            else:
                resume = _next_magic(buf, pos + 1)
        else:
            resume = _next_magic(buf, pos + 1)
        yield DroppedRegion(
            offset=pos,
            end=resume,
            reason=reason,
            checksum=checksum,
            hash=chunk.hash if chunk is not None else b"",
        )
        pos = resume


def _next_magic(buf: bytes, start: int) -> int:
    nxt = buf.find(MAGIC_BYTES, start)
    return nxt if nxt != -1 else len(buf)


def compress_chunk(chunk_bytes: bytes) -> bytes:
    """Deflate a change chunk into a compressed chunk (type 2).

    The checksum is preserved from the uncompressed chunk (reference:
    storage/change/compressed.rs).
    """
    chunk, _ = parse_chunk(chunk_bytes)
    if chunk.chunk_type != CHUNK_CHANGE:
        raise ValueError("only change chunks can be compressed")
    co = zlib.compressobj(level=6, wbits=-15)
    deflated = co.compress(chunk.data) + co.flush()
    out = bytearray(MAGIC_BYTES)
    out += chunk.checksum
    out.append(CHUNK_COMPRESSED)
    encode_uleb(len(deflated), out)
    out += deflated
    return bytes(out)
