"""Chunk framing: magic bytes, checksum, type byte, length prefix.

Byte-compatible with the reference (reference:
rust/automerge/src/storage/chunk.rs, storage.rs MAGIC_BYTES). A chunk is:

    magic (4 bytes: 85 6f 4a 83)
    checksum (4 bytes: first 4 bytes of the chunk hash)
    chunk type (1 byte: 0=document, 1=change, 2=compressed change)
    data length (ULEB128)
    data

The chunk hash — which doubles as the change hash for change chunks — is
SHA-256 over (type byte || ULEB(len) || data).
"""

from __future__ import annotations

import hashlib
import zlib
from typing import Iterator, NamedTuple

from ..utils.leb128 import decode_uleb, encode_uleb

MAGIC_BYTES = bytes([0x85, 0x6F, 0x4A, 0x83])

CHUNK_DOCUMENT = 0
CHUNK_CHANGE = 1
CHUNK_COMPRESSED = 2

DEFLATE_MIN_SIZE = 256  # reference: storage/change.rs DEFLATE_MIN_SIZE


from ..errors import AutomergeError


class ChunkParseError(AutomergeError):
    pass


def chunk_hash(chunk_type: int, data: bytes) -> bytes:
    body = bytearray([chunk_type])
    encode_uleb(len(data), body)
    body += data
    return hashlib.sha256(bytes(body)).digest()


class RawChunk(NamedTuple):
    chunk_type: int
    checksum: bytes  # 4 bytes as stored
    hash: bytes  # 32-byte SHA-256 of (type || len || data)
    data: bytes

    @property
    def checksum_valid(self) -> bool:
        return self.hash[:4] == self.checksum


def write_chunk(chunk_type: int, data: bytes) -> bytes:
    h = chunk_hash(chunk_type, data)
    out = bytearray(MAGIC_BYTES)
    out += h[:4]
    out.append(chunk_type)
    encode_uleb(len(data), out)
    out += data
    return bytes(out)


def parse_chunk(buf: bytes, pos: int = 0) -> tuple[RawChunk, int]:
    """Parse one chunk starting at ``pos``; returns (chunk, new_pos).

    Compressed change chunks are inflated transparently: the returned chunk is
    the equivalent uncompressed change chunk (its stored checksum is the
    original's, which the reference derives from the *uncompressed* data).
    """
    if pos + 8 > len(buf):
        raise ChunkParseError("truncated chunk header")
    if buf[pos : pos + 4] != MAGIC_BYTES:
        raise ChunkParseError("invalid magic bytes")
    checksum = bytes(buf[pos + 4 : pos + 8])
    if pos + 8 >= len(buf):
        raise ChunkParseError("truncated chunk header")
    chunk_type = buf[pos + 8]
    if chunk_type > CHUNK_COMPRESSED:
        raise ChunkParseError(f"unknown chunk type {chunk_type}")
    length, data_start = decode_uleb(buf, pos + 9)
    data_end = data_start + length
    if data_end > len(buf):
        raise ChunkParseError("chunk data extends past end of input")
    data = bytes(buf[data_start:data_end])
    if chunk_type == CHUNK_COMPRESSED:
        try:
            data = zlib.decompress(data, wbits=-15)  # raw DEFLATE stream
        except zlib.error as e:
            raise ChunkParseError(f"invalid deflate stream: {e}") from e
        chunk_type = CHUNK_CHANGE
    h = chunk_hash(chunk_type, data)
    return RawChunk(chunk_type, checksum, h, data), data_end


def iter_chunks(buf: bytes) -> Iterator[RawChunk]:
    pos = 0
    while pos < len(buf):
        chunk, pos = parse_chunk(buf, pos)
        yield chunk


def compress_chunk(chunk_bytes: bytes) -> bytes:
    """Deflate a change chunk into a compressed chunk (type 2).

    The checksum is preserved from the uncompressed chunk (reference:
    storage/change/compressed.rs).
    """
    chunk, _ = parse_chunk(chunk_bytes)
    if chunk.chunk_type != CHUNK_CHANGE:
        raise ValueError("only change chunks can be compressed")
    co = zlib.compressobj(level=6, wbits=-15)
    deflated = co.compress(chunk.data) + co.flush()
    out = bytearray(MAGIC_BYTES)
    out += chunk.checksum
    out.append(CHUNK_COMPRESSED)
    encode_uleb(len(deflated), out)
    out += deflated
    return bytes(out)
