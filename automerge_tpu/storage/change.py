"""Change chunk encode/decode.

Byte-compatible with the reference (reference:
rust/automerge/src/storage/change.rs, change/change_op_columns.rs,
change/change_actors.rs). Chunk body layout:

    ULEB num_deps, then 32-byte change hashes (sorted)
    ULEB actor byte length + actor bytes
    ULEB seq
    ULEB start_op
    SLEB timestamp
    ULEB message byte length + message utf8
    ULEB num_other_actors, each ULEB length-prefixed
    column metadata (see columns.py)
    op column data
    extra bytes

Actor indices inside op columns are chunk-local: index 0 is the change author,
indices 1.. are the other actors in lexicographic byte order. Op columns (by
spec): obj actor/counter (1, 2), key actor/counter/string (17, 19, 21),
insert (52), action (66), value meta/raw (86, 87), pred group/actor/counter
(112, 113, 115), expand (148), mark name (165).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

from ..types import HEAD, Key, OpId, ScalarValue, is_head, is_root
from ..utils.codecs import (
    BooleanEncoder,
    DeltaEncoder,
    MaybeBooleanEncoder,
    RleEncoder,
    boolean_decode,
    delta_decode,
    rle_decode,
)
from ..utils.leb128 import decode_sleb, decode_uleb, encode_sleb, encode_uleb
from . import columns as C
from .chunk import CHUNK_CHANGE, chunk_hash, parse_chunk, write_chunk
from .values import ValueEncoder, decode_values

# Normalized column specs for change op columns
COL_OBJ_ACTOR = C.spec(0, C.TYPE_ACTOR)  # 1
COL_OBJ_CTR = C.spec(0, C.TYPE_INTEGER)  # 2
COL_KEY_ACTOR = C.spec(1, C.TYPE_ACTOR)  # 17
COL_KEY_CTR = C.spec(1, C.TYPE_DELTA)  # 19
COL_KEY_STR = C.spec(1, C.TYPE_STRING)  # 21
COL_INSERT = C.spec(3, C.TYPE_BOOLEAN)  # 52
COL_ACTION = C.spec(4, C.TYPE_INTEGER)  # 66
COL_VAL_META = C.spec(5, C.TYPE_VALUE_META)  # 86
COL_VAL_RAW = C.spec(5, C.TYPE_VALUE)  # 87
COL_PRED_GROUP = C.spec(7, C.TYPE_GROUP)  # 112
COL_PRED_ACTOR = C.spec(7, C.TYPE_ACTOR)  # 113
COL_PRED_CTR = C.spec(7, C.TYPE_DELTA)  # 115
COL_EXPAND = C.spec(9, C.TYPE_BOOLEAN)  # 148
COL_MARK_NAME = C.spec(10, C.TYPE_STRING)  # 165


@dataclass
class ChangeOp:
    """One op as stored in a change chunk.

    ``obj``/``key.elem``/``pred`` op ids carry chunk-local actor indices.
    obj == ROOT is represented as (0, -1) here to distinguish "root" from
    "op of actor 0"; elem HEAD is (0, -1) likewise.
    """

    obj: OpId
    key: Key
    insert: bool
    action: int
    value: ScalarValue
    pred: List[OpId] = field(default_factory=list)
    expand: bool = False
    mark_name: Optional[str] = None


ROOT_STORED: OpId = (0, -1)
HEAD_STORED: OpId = (0, -1)


@dataclass
class StoredChange:
    """A parsed or built change chunk."""

    dependencies: List[bytes]
    actor: bytes  # author actor id bytes
    other_actors: List[bytes]
    seq: int
    start_op: int
    timestamp: int
    message: Optional[str]
    ops: List[ChangeOp]
    extra_bytes: bytes = b""
    # Set when built/parsed:
    hash: Optional[bytes] = None
    raw_bytes: Optional[bytes] = None  # whole chunk incl. header
    # Raw op-column bytes (spec -> bytes), kept for the vectorized
    # column-to-array extraction path (ops/extract.py).
    op_col_data: Optional[dict] = None
    # Decoded chunk-local column arrays (ops/assemble.ChangeCols),
    # attached at commit time or memoized on first decode so merges
    # never re-decode the chunk (the "commit-time column cache").
    cached_cols: Optional[object] = None

    @property
    def actors(self) -> List[bytes]:
        """Chunk-local actor table: author first, then others sorted."""
        return [self.actor, *self.other_actors]

    @property
    def max_op(self) -> int:
        return self.start_op + len(self.ops) - 1 if self.ops else self.start_op - 1


def encode_change_ops(ops: Sequence[ChangeOp]) -> List[Tuple[int, bytes]]:
    """Encode op columns; returns [(normalized spec, bytes)] in order."""
    obj_actor = RleEncoder("uint")
    obj_ctr = RleEncoder("uint")
    key_actor = RleEncoder("uint")
    key_ctr = DeltaEncoder()
    key_str = RleEncoder("str")
    insert = BooleanEncoder()
    action = RleEncoder("uint")
    val = ValueEncoder()
    pred_num = RleEncoder("uint")
    pred_actor = RleEncoder("uint")
    pred_ctr = DeltaEncoder()
    expand = MaybeBooleanEncoder()
    mark_name = RleEncoder("str")

    for op in ops:
        # Root and HEAD are identified by counter 0 alone — both the public
        # (0, 0) sentinels (types.ROOT/HEAD) and the storage-layer (0, -1)
        # forms encode identically (no real op has counter 0).
        if is_root(op.obj):
            obj_actor.append_null()
            obj_ctr.append_null()
        else:
            obj_actor.append_value(op.obj[1])
            obj_ctr.append_value(op.obj[0])
        if op.key.prop is not None:
            key_actor.append_null()
            key_ctr.append(None)
            key_str.append_value(op.key.prop)
        elif is_head(op.key.elem):
            key_actor.append_null()
            key_ctr.append(0)
            key_str.append_null()
        else:
            key_actor.append_value(op.key.elem[1])
            key_ctr.append(op.key.elem[0])
            key_str.append_null()
        insert.append(op.insert)
        action.append_value(op.action)
        val.append(op.value)
        pred_num.append_value(len(op.pred))
        for p in op.pred:
            pred_actor.append_value(p[1])
            pred_ctr.append(p[0])
        expand.append(op.expand)
        if op.mark_name is None:
            mark_name.append_null()
        else:
            mark_name.append_value(op.mark_name)

    val_meta, val_raw = val.finish()
    return [
        (COL_OBJ_ACTOR, obj_actor.finish()),
        (COL_OBJ_CTR, obj_ctr.finish()),
        (COL_KEY_ACTOR, key_actor.finish()),
        (COL_KEY_CTR, key_ctr.finish()),
        (COL_KEY_STR, key_str.finish()),
        (COL_INSERT, insert.finish()),
        (COL_ACTION, action.finish()),
        (COL_VAL_META, val_meta),
        (COL_VAL_RAW, val_raw),
        (COL_PRED_GROUP, pred_num.finish()),
        (COL_PRED_ACTOR, pred_actor.finish()),
        (COL_PRED_CTR, pred_ctr.finish()),
        (COL_EXPAND, expand.finish()),
        (COL_MARK_NAME, mark_name.finish()),
    ]


def encode_ops_with_tail(prefix_ops: Sequence[ChangeOp], tail) -> List[Tuple[int, bytes]]:
    """Encode op columns for ``prefix_ops`` (chunk-local ChangeOps) followed
    by a numpy tail from the native edit session — identical bytes to
    ``encode_change_ops`` over the materialized op list, at array speed.

    ``tail`` fields (chunk-local actor indices, one row per op):
      obj_ctr/obj_actor   ints (the session's single object id)
      elem_ctr (i64), elem_actor (i64, -1 = HEAD/null)
      insert (u8), action (i64)
      val_meta (i64: (byte_len << 4) | type_code), val_raw (bytes)
      pred_ctr/pred_actor (i64, -1 = no pred)
    """
    import numpy as np

    from .. import native
    from .values import encode_raw_value, value_meta

    np_ = len(prefix_ops)
    nt = len(tail["action"])
    n = np_ + nt

    obj_ctr = np.empty(n, np.int64)
    obj_mask = np.empty(n, np.uint8)
    obj_actor = np.empty(n, np.int64)
    key_ctr = np.empty(n, np.int64)
    key_ctr_mask = np.empty(n, np.uint8)
    key_actor = np.empty(n, np.int64)
    key_actor_mask = np.empty(n, np.uint8)
    insert = np.empty(n, np.uint8)
    action = np.empty(n, np.int64)
    vmeta = np.empty(n, np.int64)
    pred_num = np.empty(n, np.int64)

    key_str = RleEncoder("str")
    mark_name = RleEncoder("str")
    expand = MaybeBooleanEncoder()
    raw = bytearray()
    pred_ctr_list: List[int] = []
    pred_actor_list: List[int] = []

    for i, op in enumerate(prefix_ops):
        if is_root(op.obj):
            obj_mask[i] = 0
            obj_ctr[i] = 0
            obj_actor[i] = 0
        else:
            obj_mask[i] = 1
            obj_ctr[i] = op.obj[0]
            obj_actor[i] = op.obj[1]
        if op.key.prop is not None:
            key_str.append_value(op.key.prop)
            key_ctr_mask[i] = 0
            key_ctr[i] = 0
            key_actor_mask[i] = 0
            key_actor[i] = 0
        elif is_head(op.key.elem):
            key_str.append_null()
            key_ctr_mask[i] = 1
            key_ctr[i] = 0
            key_actor_mask[i] = 0
            key_actor[i] = 0
        else:
            key_str.append_null()
            key_ctr_mask[i] = 1
            key_ctr[i] = op.key.elem[0]
            key_actor_mask[i] = 1
            key_actor[i] = op.key.elem[1]
        insert[i] = 1 if op.insert else 0
        action[i] = op.action
        vmeta[i] = value_meta(op.value)
        encode_raw_value(op.value, raw)
        pred_num[i] = len(op.pred)
        for p in op.pred:
            pred_ctr_list.append(p[0])
            pred_actor_list.append(p[1])
        expand.append(op.expand)
        if op.mark_name is None:
            mark_name.append_null()
        else:
            mark_name.append_value(op.mark_name)

    # tail (vectorized)
    s = slice(np_, n)
    obj_mask[s] = 1
    obj_ctr[s] = int(tail["obj_ctr"])
    obj_actor[s] = int(tail["obj_actor"])
    t_elem_actor = tail["elem_actor"]
    key_ctr[s] = tail["elem_ctr"]
    key_ctr_mask[s] = 1
    key_actor[s] = np.where(t_elem_actor >= 0, t_elem_actor, 0)
    key_actor_mask[s] = (t_elem_actor >= 0).astype(np.uint8)
    insert[s] = tail["insert"]
    action[s] = tail["action"]
    vmeta[s] = tail["val_meta"]
    raw += tail["val_raw"]
    t_pred_ctr = tail["pred_ctr"]
    has_pred = t_pred_ctr >= 0
    pred_num[s] = has_pred.astype(np.int64)
    key_str.append_null_run(nt)
    mark_name.append_null_run(nt)
    expand.append_run(False, nt)

    pred_ctr_all = np.concatenate(
        [np.asarray(pred_ctr_list, np.int64), t_pred_ctr[has_pred]]
    )
    pred_actor_all = np.concatenate(
        [np.asarray(pred_actor_list, np.int64), tail["pred_actor"][has_pred]]
    )
    ones_p = np.ones(len(pred_ctr_all), np.uint8)
    ones = np.ones(n, np.uint8)

    return [
        (COL_OBJ_ACTOR, native.rle_encode_array(obj_actor, obj_mask, False)),
        (COL_OBJ_CTR, native.rle_encode_array(obj_ctr, obj_mask, False)),
        (COL_KEY_ACTOR, native.rle_encode_array(key_actor, key_actor_mask, False)),
        (COL_KEY_CTR, native.delta_encode_array(key_ctr, key_ctr_mask)),
        (COL_KEY_STR, key_str.finish()),
        (COL_INSERT, native.bool_encode_array(insert)),
        (COL_ACTION, native.rle_encode_array(action, ones, False)),
        (COL_VAL_META, native.rle_encode_array(vmeta, ones, False)),
        (COL_VAL_RAW, bytes(raw)),
        (COL_PRED_GROUP, native.rle_encode_array(pred_num, ones, False)),
        (COL_PRED_ACTOR, native.rle_encode_array(pred_actor_all, ones_p, False)),
        (COL_PRED_CTR, native.delta_encode_array(pred_ctr_all, ones_p)),
        (COL_EXPAND, expand.finish()),
        (COL_MARK_NAME, mark_name.finish()),
    ]


def encode_map_tail_cols(tail) -> List[Tuple[int, bytes]]:
    """Encode op columns for a pure map-put change from the native map
    session (no prefix rows) — identical bytes to ``encode_change_ops``
    over the materialized op list, at array speed.

    ``tail`` fields (chunk-local actor indices, one row per op):
      obj_ctr/obj_actor   ints (the session's object; obj_actor -1 = root)
      key_idx (i64 into keys), keys (string table)
      val_meta (i64: (byte_len << 4) | type_code), val_raw (bytes)
      pred_ctr/pred_actor (i64, -1 = no pred)
    """
    import numpy as np

    from .. import native
    from ..types import Action

    n = len(tail["key_idx"])
    ones = np.ones(n, np.uint8)
    zero_mask = np.zeros(n, np.uint8)
    zeros = np.zeros(n, np.int64)

    root = int(tail["obj_actor"]) < 0
    obj_mask = zero_mask if root else ones
    obj_ctr = zeros if root else np.full(n, int(tail["obj_ctr"]), np.int64)
    obj_actor = zeros if root else np.full(n, int(tail["obj_actor"]), np.int64)

    action = np.full(n, int(Action.PUT), np.int64)
    t_pred_ctr = np.asarray(tail["pred_ctr"], np.int64)
    has_pred = t_pred_ctr >= 0
    pred_ctr = t_pred_ctr[has_pred]
    pred_actor = np.asarray(tail["pred_actor"], np.int64)[has_pred]
    ones_p = np.ones(len(pred_ctr), np.uint8)

    expand = MaybeBooleanEncoder()
    expand.append_run(False, n)
    mark_name = RleEncoder("str")
    mark_name.append_null_run(n)

    return [
        (COL_OBJ_ACTOR, native.rle_encode_array(obj_actor, obj_mask, False)),
        (COL_OBJ_CTR, native.rle_encode_array(obj_ctr, obj_mask, False)),
        (COL_KEY_ACTOR, native.rle_encode_array(zeros, zero_mask, False)),
        (COL_KEY_CTR, native.delta_encode_array(zeros, zero_mask)),
        (COL_KEY_STR, native.rle_encode_strtab(
            np.asarray(tail["key_idx"], np.int64), tail["keys"])),
        (COL_INSERT, native.bool_encode_array(zero_mask)),
        (COL_ACTION, native.rle_encode_array(action, ones, False)),
        (COL_VAL_META, native.rle_encode_array(
            np.asarray(tail["val_meta"], np.int64), ones, False)),
        (COL_VAL_RAW, bytes(tail["val_raw"])),
        (COL_PRED_GROUP, native.rle_encode_array(
            has_pred.astype(np.int64), ones, False)),
        (COL_PRED_ACTOR, native.rle_encode_array(pred_actor, ones_p, False)),
        (COL_PRED_CTR, native.delta_encode_array(pred_ctr, ones_p)),
        (COL_EXPAND, expand.finish()),
        (COL_MARK_NAME, mark_name.finish()),
    ]


def encode_change_cols_arrays(a) -> List[Tuple[int, bytes]]:
    """Full-array change-op column encode — byte-identical to
    ``encode_change_ops`` over the materialized ChangeOp list (the fast
    document-load path re-encoding reconstructed changes for hashing).

    ``a`` fields, all length n in op-id order with chunk-local actor
    indices: obj_ctr/obj_actor/obj_mask, key_str_ids (+key_str_table),
    key_ctr/key_ctr_mask/key_actor/key_actor_mask, insert (u8), action,
    val_meta, val_raw (bytes), pred_num, pred_ctr/pred_actor (flat),
    expand (u8), mark_ids (+mark_table).
    """
    import numpy as np

    from .. import native
    from ..utils.codecs import _bool_runs_col, _str_runs_col

    def str_col(ids, table) -> bytes:
        try:
            return native.rle_encode_strtab(ids, table)
        except native.NativeUnavailable:
            return _str_runs_col(ids, table, RleEncoder("str"))

    n = len(a["action"])
    ones = np.ones(n, np.uint8)
    ones_p = np.ones(len(a["pred_ctr"]), np.uint8)
    return [
        (COL_OBJ_ACTOR, native.rle_encode_array(a["obj_actor"], a["obj_mask"], False)),
        (COL_OBJ_CTR, native.rle_encode_array(a["obj_ctr"], a["obj_mask"], False)),
        (COL_KEY_ACTOR, native.rle_encode_array(a["key_actor"], a["key_actor_mask"], False)),
        (COL_KEY_CTR, native.delta_encode_array(a["key_ctr"], a["key_ctr_mask"])),
        (COL_KEY_STR, str_col(a["key_str_ids"], a["key_str_table"])),
        (COL_INSERT, native.bool_encode_array(a["insert"])),
        (COL_ACTION, native.rle_encode_array(a["action"], ones, False)),
        (COL_VAL_META, native.rle_encode_array(a["val_meta"], ones, False)),
        (COL_VAL_RAW, a["val_raw"]),
        (COL_PRED_GROUP, native.rle_encode_array(a["pred_num"], ones, False)),
        (COL_PRED_ACTOR, native.rle_encode_array(a["pred_actor"], ones_p, False)),
        (COL_PRED_CTR, native.delta_encode_array(a["pred_ctr"], ones_p)),
        (COL_EXPAND, _bool_runs_col(a["expand"], MaybeBooleanEncoder())),
        (COL_MARK_NAME, str_col(a["mark_ids"], a["mark_table"])),
    ]


def decode_change_ops(col_data: dict[int, bytes]) -> List[ChangeOp]:
    """Decode op columns from a dict of normalized spec -> bytes."""

    def col(s):
        return col_data.get(s, b"")

    # Row count is the longest primary column; every column must then cover
    # (or legitimately null-fill) all n rows — truncation is a parse error.
    actions = rle_decode(col(COL_ACTION), "uint")
    key_str = rle_decode(col(COL_KEY_STR), "str")
    key_ctr = delta_decode(col(COL_KEY_CTR))
    n = max(len(actions), len(key_str), len(key_ctr))
    insert = boolean_decode(col(COL_INSERT), n)
    actions = _pad(actions, n)
    obj_actor = _pad(rle_decode(col(COL_OBJ_ACTOR), "uint"), n)
    obj_ctr = _pad(rle_decode(col(COL_OBJ_CTR), "uint"), n)
    key_actor = _pad(rle_decode(col(COL_KEY_ACTOR), "uint"), n)
    key_ctr = _pad(key_ctr, n)
    key_str = _pad(key_str, n)
    values = decode_values(col(COL_VAL_META), col(COL_VAL_RAW), n)
    pred_num = _pad(rle_decode(col(COL_PRED_GROUP), "uint"), n)
    total_preds = sum(p or 0 for p in pred_num)
    pred_actor = rle_decode(col(COL_PRED_ACTOR), "uint", total_preds)
    pred_ctr = delta_decode(col(COL_PRED_CTR), total_preds)
    expand = boolean_decode(col(COL_EXPAND), n)
    mark_name = _pad(rle_decode(col(COL_MARK_NAME), "str"), n)

    ops: List[ChangeOp] = []
    pi = 0
    for i in range(n):
        if actions[i] is None:
            raise ValueError(f"op {i}: missing action")
        obj = _decode_objid(obj_ctr[i], obj_actor[i], i)
        if key_str[i] is not None:
            key = Key.map(key_str[i])
        elif key_ctr[i] == 0 and key_actor[i] is None:
            key = Key.seq(HEAD_STORED)
        elif key_ctr[i] is not None and key_actor[i] is not None:
            key = Key.seq((key_ctr[i], key_actor[i]))
        else:
            raise ValueError(f"op {i}: neither map key nor elem id present")
        np = pred_num[i] or 0
        pred = []
        for _ in range(np):
            if pi >= len(pred_ctr) or pred_ctr[pi] is None or pred_actor[pi] is None:
                raise ValueError(f"op {i}: truncated pred column")
            pred.append((pred_ctr[pi], pred_actor[pi]))
            pi += 1
        ops.append(
            ChangeOp(
                obj=obj,
                key=key,
                insert=insert[i],
                action=actions[i],
                value=values[i],
                pred=pred,
                expand=expand[i],
                mark_name=mark_name[i],
            )
        )
    return ops


def _decode_objid(ctr, actor, i: int) -> OpId:
    """Decode an obj id column pair: both null = root, both set = op id."""
    if ctr is None and actor is None:
        return ROOT_STORED
    if ctr is None or actor is None:
        raise ValueError(f"op {i}: half-null object id")
    return (ctr, actor)


def _pad(lst: list, n: int) -> list:
    if len(lst) < n:
        lst.extend([None] * (n - len(lst)))
    return lst


class LazyOps:
    """List-like view over a change's ops, decoded from the retained column
    bytes on first element access. ``len`` is always O(1); the hot paths
    (bulk rebuild, device extraction) read ``op_col_data`` directly and
    never materialize ChangeOp objects."""

    __slots__ = ("_col_data", "_n", "_ops")

    def __init__(self, col_data: dict, n: int):
        self._col_data = col_data
        self._n = n
        self._ops = None

    def _mat(self) -> List[ChangeOp]:
        if self._ops is None:
            self._ops = decode_change_ops(self._col_data)
        return self._ops

    def __len__(self) -> int:
        return self._n

    def __bool__(self) -> bool:
        return self._n > 0

    def __iter__(self):
        return iter(self._mat())

    def __getitem__(self, i):
        return self._mat()[i]


def build_change(change: StoredChange, cols=None) -> StoredChange:
    """Encode ``change`` into chunk bytes, filling ``hash``/``raw_bytes``.

    ``cols`` supplies precomputed op columns (the array-native commit
    path); when given, ``change.ops`` may be a LazyOps placeholder."""
    data = bytearray()
    deps = sorted(change.dependencies)
    change.dependencies = deps
    encode_uleb(len(deps), data)
    for d in deps:
        if len(d) != 32:
            raise ValueError("change hash must be 32 bytes")
        data += d
    encode_uleb(len(change.actor), data)
    data += change.actor
    encode_uleb(change.seq, data)
    if change.start_op < 1:
        raise ValueError("start_op must be >= 1")
    encode_uleb(change.start_op, data)
    encode_sleb(change.timestamp, data)
    msg = (change.message or "").encode("utf-8")
    encode_uleb(len(msg), data)
    data += msg
    encode_uleb(len(change.other_actors), data)
    for a in change.other_actors:
        encode_uleb(len(a), data)
        data += a
    if cols is None:
        cols = encode_change_ops(change.ops)
    C.write_columns(cols, data)
    data += change.extra_bytes
    raw = write_chunk(CHUNK_CHANGE, bytes(data))
    change.hash = chunk_hash(CHUNK_CHANGE, bytes(data))
    change.raw_bytes = raw
    change.op_col_data = dict(cols)
    return change


def parse_change_data(data: bytes, chunk_hash_: bytes, raw: bytes) -> StoredChange:
    """Parse the body of a change chunk (after the chunk header)."""
    pos = 0
    ndeps, pos = decode_uleb(data, pos)
    deps = []
    for _ in range(ndeps):
        if pos + 32 > len(data):
            raise ValueError("truncated change deps")
        deps.append(bytes(data[pos : pos + 32]))
        pos += 32
    alen, pos = decode_uleb(data, pos)
    actor = bytes(data[pos : pos + alen])
    if len(actor) != alen:
        raise ValueError("truncated actor id")
    pos += alen
    seq, pos = decode_uleb(data, pos)
    start_op, pos = decode_uleb(data, pos)
    if start_op < 1:
        raise ValueError("start_op must be >= 1")
    if start_op > 0xFFFFFFFF:
        raise ValueError("op counter too large")  # reference rejects > u32
    timestamp, pos = decode_sleb(data, pos)
    mlen, pos = decode_uleb(data, pos)
    message = data[pos : pos + mlen].decode("utf-8")
    pos += mlen
    nother, pos = decode_uleb(data, pos)
    others = []
    for _ in range(nother):
        olen, pos = decode_uleb(data, pos)
        others.append(bytes(data[pos : pos + olen]))
        pos += olen
    metas, pos = C.parse_columns(data, pos)
    for s, _ in metas:
        if C.spec_deflate(s):
            raise ValueError("change chunks must not contain compressed columns")
    col_data = C.slice_column_data(data, metas, pos)
    pos += C.total_column_len(metas)
    extra = bytes(data[pos:])
    ops = decode_change_ops(col_data)
    _saved_col_data = dict(col_data)
    n_actors = 1 + len(others)
    for i, op in enumerate(ops):
        _check_actor_bounds(op, i, n_actors)
    return StoredChange(
        dependencies=deps,
        actor=actor,
        other_actors=others,
        seq=seq,
        start_op=start_op,
        timestamp=timestamp,
        message=message or None,
        ops=ops,
        extra_bytes=extra,
        hash=chunk_hash_,
        raw_bytes=raw,
        op_col_data=_saved_col_data,
    )


def _check_actor_bounds(op: ChangeOp, i: int, n_actors: int) -> None:
    refs = []
    if op.obj != ROOT_STORED:
        refs.append(op.obj[1])
    if op.key.elem is not None and op.key.elem != HEAD_STORED:
        refs.append(op.key.elem[1])
    refs.extend(p[1] for p in op.pred)
    for a in refs:
        if a < 0 or a >= n_actors:
            raise ValueError(f"op {i} references missing actor index {a}")


def parse_change(buf: bytes, pos: int = 0) -> tuple[StoredChange, int]:
    chunk, end = parse_chunk(buf, pos)
    if chunk.chunk_type != CHUNK_CHANGE:
        raise ValueError(f"expected change chunk, got type {chunk.chunk_type}")
    if not chunk.checksum_valid:
        raise ValueError("change chunk checksum mismatch")
    if buf[pos + 8] == 2:  # was stored compressed: rebuild uncompressed chunk
        raw = write_chunk(CHUNK_CHANGE, chunk.data)
    else:
        raw = bytes(buf[pos:end])
    change = parse_change_data(chunk.data, chunk.hash, raw)
    return change, end


def chunk_local_ops(rows, author, actor_bytes_of, extra_refs=()):
    """Translate ops with *global* actor indices into chunk-local ChangeOps.

    Builds the chunk-local actor table — author first, remaining referenced
    actors sorted by their bytes (reference: change/change_actors.rs) — and
    rewrites obj / elem / pred references through it. ``rows`` are ChangeOp-
    shaped records whose OpIds carry global indices; ``actor_bytes_of`` maps
    a global index to actor bytes; ``extra_refs`` adds global indices
    referenced outside ``rows`` (the native-session tail) to the table.
    Returns (chunk_ops, other_global_indices, local_of_global).

    This is the single encoder shared by transaction commit and document
    save/reconstruct so both always produce byte-identical change chunks for
    the same logical change.
    """
    other: List[int] = []
    seen = {author}
    for r in rows:
        refs = []
        if r.obj != ROOT_STORED:
            refs.append(r.obj[1])
        if r.key.elem is not None and r.key.elem[0] != 0:
            refs.append(r.key.elem[1])
        refs.extend(p[1] for p in r.pred)
        for a in refs:
            if a not in seen:
                seen.add(a)
                other.append(a)
    for a in extra_refs:
        a = int(a)
        if a not in seen:
            seen.add(a)
            other.append(a)
    other.sort(key=actor_bytes_of)
    local = {author: 0}
    for j, g in enumerate(other):
        local[g] = j + 1

    def tr(opid: OpId) -> OpId:
        return (opid[0], local[opid[1]])

    ops = []
    for r in rows:
        if r.key.prop is not None:
            key = r.key
        elif r.key.elem[0] == 0:
            key = Key.seq(HEAD_STORED)
        else:
            key = Key.seq(tr(r.key.elem))
        ops.append(
            ChangeOp(
                obj=ROOT_STORED if r.obj == ROOT_STORED else tr(r.obj),
                key=key,
                insert=r.insert,
                action=r.action,
                value=r.value,
                pred=[tr(p) for p in r.pred],
                expand=r.expand,
                mark_name=r.mark_name,
            )
        )
    return ops, other, local
