// Native text-edit session: the local-transaction hot path.
//
// The reference replays its edit trace through Rust (criterion
// rust/edit-trace/benches/main.rs splice loop over
// transaction/inner.rs:600-714 inner_splice); the Python transaction
// layer cannot match that per-op. This session owns ONE text object's
// visible-element state for the duration of a transaction: splices are
// resolved (position seek, mid-element rewind, delete walk, insert
// chaining) entirely in C++, and the emitted ops are exported as arrays
// for the array-native change encoder at commit. Deleted elements are
// physically unlinked — a session list never accumulates tombstone
// deserts, so the position cursor walk stays O(edit locality).
//
// Eligibility is gated by the Python wrapper: TEXT object, no marks, no
// multi-winner (conflicted) elements, no isolation scope. Ids pack as
// (counter << 20 | doc actor index); the wrapper translates to
// chunk-local actor tables at commit.

#include <cstddef>
#include <cstdint>
#include <algorithm>
#include <vector>

using std::size_t;

namespace {

using i64 = long long;
using i32 = int32_t;
using u8 = uint8_t;

constexpr i32 NONE = -1;

struct SElem {
  i64 id;       // element (insert op) id, packed
  i64 winner;   // current visible op id (pred target for deletes)
  i32 width;    // text width in the configured encoding unit
  i32 prev = NONE, next = NONE;
};

struct EOp {     // one emitted op, in id (emission) order
  i64 id;        // packed (ctr << 20 | rank)
  i64 elem_ref;  // insert: RGA reference element (0 = HEAD); delete: target
  i64 pred;      // delete: overwritten winner id; insert: 0
  i32 cp;        // insert: unicode codepoint; delete: -1
  i32 width;     // insert: width of this codepoint
  u8 is_del;
};

struct Session {
  std::vector<SElem> elems;  // slot-addressed; unlinked slots stay (ids live)
  std::vector<EOp> ops;
  i32 head = NONE, tail = NONE;
  i64 total_width = 0;
  i64 rank = 0;  // author's packed-id rank (doc actor index)
  // moving cursor: slot whose span starts at cur_at (NONE = unseeded)
  i32 cur = NONE;
  i64 cur_at = 0;
  // anchor index for random-position seeks (the session analogue of the
  // host store's block order-statistics index, core/op_store.py): every
  // kAnchorStride-th visible element with its span start, sorted by
  // position. Built lazily on the first far seek — sequential typing
  // (cursor always near) never pays for it — and maintained per splice:
  // anchors inside a deleted span drop, later anchors shift by the width
  // delta, and a rebuild re-amortizes after kAnchorRebuild mutations.
  std::vector<i32> anc_slot;
  std::vector<i64> anc_at;
  i64 anc_muts = 0;
  bool anc_dirty = true;
};

constexpr i64 kAnchorStride = 512;
constexpr i64 kAnchorRebuild = 4096;

void anc_rebuild(Session& s) {
  s.anc_slot.clear();
  s.anc_at.clear();
  i64 a = 0, count = 0;
  for (i32 slot = s.head; slot != NONE; slot = s.elems[slot].next) {
    if (count % kAnchorStride == 0) {
      s.anc_slot.push_back(slot);
      s.anc_at.push_back(a);
    }
    a += s.elems[slot].width;
    count++;
  }
  s.anc_muts = 0;
  s.anc_dirty = false;
}

// Splice bookkeeping: drop anchors inside the deleted span [pos, pos+del_w),
// shift anchors at or past the splice point by the width delta.
void anc_after_splice(Session& s, i64 pos, i64 del_w, i64 ins_w) {
  if (s.anc_dirty) return;
  if (++s.anc_muts > kAnchorRebuild) {
    s.anc_dirty = true;
    return;
  }
  size_t lo = (size_t)(std::lower_bound(s.anc_at.begin(), s.anc_at.end(), pos) -
                       s.anc_at.begin());
  size_t hi = (size_t)(std::lower_bound(s.anc_at.begin(), s.anc_at.end(),
                                        pos + del_w) -
                       s.anc_at.begin());
  if (hi > lo) {
    s.anc_slot.erase(s.anc_slot.begin() + lo, s.anc_slot.begin() + hi);
    s.anc_at.erase(s.anc_at.begin() + lo, s.anc_at.begin() + hi);
  }
  const i64 delta = ins_w - del_w;
  if (delta)
    for (size_t i = lo; i < s.anc_at.size(); i++) s.anc_at[i] += delta;
}

// Find the visible element covering width-position `pos`; returns slot (or
// NONE past the end) and writes its span start to *at. Walks from the
// cursor when near, else from an index anchor, else from the closer end.
i32 seek(Session& s, i64 pos, i64* at) {
  i32 slot;
  i64 a;
  i64 from_front = pos;
  i64 from_back = s.total_width - pos;
  i64 from_cur = s.cur == NONE ? from_front + 1 : (pos > s.cur_at ? pos - s.cur_at : s.cur_at - pos);
  i64 best = from_cur < from_front ? from_cur : from_front;
  if (from_back < best) best = from_back;
  if (best > 2 * kAnchorStride) {
    if (s.anc_dirty && s.elems.size() > (size_t)(4 * kAnchorStride))
      anc_rebuild(s);
    if (!s.anc_dirty && !s.anc_at.empty()) {
      size_t idx = (size_t)(std::upper_bound(s.anc_at.begin(), s.anc_at.end(),
                                             pos) -
                            s.anc_at.begin());
      if (idx > 0 && pos - s.anc_at[idx - 1] < best) {
        slot = s.anc_slot[idx - 1];
        a = s.anc_at[idx - 1];
        goto walk;
      }
    }
  }
  if (s.cur != NONE && from_cur <= from_front && from_cur <= from_back) {
    slot = s.cur;
    a = s.cur_at;
  } else if (from_front <= from_back) {
    slot = s.head;
    a = 0;
  } else {
    slot = s.tail;
    a = s.total_width - (s.tail == NONE ? 0 : s.elems[s.tail].width);
  }
walk:
  // walk backward while pos is before the span
  while (slot != NONE && pos < a) {
    slot = s.elems[slot].prev;
    if (slot != NONE) a -= s.elems[slot].width;
  }
  if (slot == NONE && pos >= 0 && s.head != NONE && pos < s.total_width) {
    slot = s.head;
    a = 0;
  }
  // walk forward while pos is past the span
  while (slot != NONE && pos >= a + s.elems[slot].width) {
    a += s.elems[slot].width;
    slot = s.elems[slot].next;
  }
  *at = a;
  return slot;
}

}  // namespace

extern "C" {

void* am_edit_create(i64 rank) {
  auto* s = new Session();
  s->rank = rank;
  return s;
}

void am_edit_destroy(void* p) { delete static_cast<Session*>(p); }

// Preload the object's visible elements in document order. Each carries
// its element id, current winner id, and width. Returns 0.
i64 am_edit_init(void* p, const i64* elem_ids, const i64* winner_ids,
                 const i32* widths, i64 n) {
  Session& s = *static_cast<Session*>(p);
  s.elems.reserve((size_t)n + 1024);
  i32 prev = NONE;
  for (i64 i = 0; i < n; i++) {
    SElem el;
    el.id = elem_ids[i];
    el.winner = winner_ids[i];
    el.width = widths[i];
    el.prev = prev;
    i32 slot = (i32)s.elems.size();
    s.elems.push_back(el);
    if (prev == NONE)
      s.head = slot;
    else
      s.elems[prev].next = slot;
    prev = slot;
    s.total_width += widths[i];
  }
  s.tail = prev;
  return 0;
}

i64 am_edit_length(void* p) { return static_cast<Session*>(p)->total_width; }

i64 am_edit_op_count(void* p) {
  return (i64)static_cast<Session*>(p)->ops.size();
}

namespace {
// one splice: returns ops emitted or a negative error (-1 pos OOB, -2
// delete past end)
i64 splice_impl(Session& s, i64 ctr0, i64 pos, i64 ndel, const i32* cps,
                const i32* widths, i64 ncp) {
  if (pos < 0 || pos > s.total_width) return -1;
  i64 ctr = ctr0;
  i64 emitted = 0;

  // mid-element rewind (reference inner_splice adjusted_index,
  // transaction/inner.rs:631-637): a delete starting inside a multi-width
  // element expands to cover it from its start
  i64 at;
  if (ndel > 0) {
    i32 t = seek(s, pos, &at);
    if (t != NONE && at < pos) {
      ndel += pos - at;
      pos = at;
    }
  }

  // anchor: visible element covering pos-1 (NONE = HEAD)
  i32 anchor = NONE;
  i64 anchor_at = 0;
  if (pos > 0) {
    anchor = seek(s, pos - 1, &anchor_at);
    if (anchor == NONE) return -1;
  }

  // deletes: walk forward from the anchor, unlink each element
  i64 remaining = ndel;
  i64 del_w = 0;
  i32 cur = anchor == NONE ? s.head : s.elems[anchor].next;
  while (remaining > 0) {
    if (cur == NONE) {
      // elements were already unlinked; the anchor index would otherwise
      // keep trusting their slots/positions for up to kAnchorRebuild muts
      s.anc_dirty = true;
      return -2;
    }
    SElem& el = s.elems[cur];
    EOp op;
    op.id = (ctr << 20) | s.rank;
    op.elem_ref = el.id;
    op.pred = el.winner;
    op.cp = -1;
    op.width = 0;
    op.is_del = 1;
    s.ops.push_back(op);
    ctr++;
    emitted++;
    remaining -= el.width;
    del_w += el.width;
    s.total_width -= el.width;
    i32 nxt = el.next;
    if (el.prev == NONE)
      s.head = nxt;
    else
      s.elems[el.prev].next = nxt;
    if (nxt == NONE)
      s.tail = el.prev;
    else
      s.elems[nxt].prev = el.prev;
    cur = nxt;
  }

  // inserts: chain after the anchor (ref = previous element id; no marks
  // in session objects, so the sticky-boundary scan reduces to the anchor)
  i32 prev = anchor;
  i64 ins_w = 0;
  i64 ref = anchor == NONE ? 0 : s.elems[anchor].id;
  for (i64 i = 0; i < ncp; i++) {
    i64 id = (ctr << 20) | s.rank;
    EOp op;
    op.id = id;
    op.elem_ref = ref;
    op.pred = 0;
    op.cp = cps[i];
    op.width = widths[i];
    op.is_del = 0;
    s.ops.push_back(op);
    ctr++;
    emitted++;
    SElem el;
    el.id = id;
    el.winner = id;
    el.width = widths[i];
    el.prev = prev;
    el.next = prev == NONE ? s.head : s.elems[prev].next;
    i32 slot = (i32)s.elems.size();
    s.elems.push_back(el);
    if (el.prev == NONE)
      s.head = slot;
    else
      s.elems[el.prev].next = slot;
    if (el.next == NONE)
      s.tail = slot;
    else
      s.elems[el.next].prev = slot;
    prev = slot;
    ref = id;
    ins_w += widths[i];
    s.total_width += widths[i];
  }
  anc_after_splice(s, pos, del_w, ins_w);

  // reseed the cursor at the anchor's (authoritative) span start — the
  // anchor is never deleted by this splice, so both are still valid
  if (anchor != NONE) {
    s.cur = anchor;
    s.cur_at = anchor_at;
  } else {
    s.cur = s.head;
    s.cur_at = 0;
  }
  return emitted;
}
}  // namespace

// Splice: delete `ndel` width units at `pos`, then insert `ncp` codepoints
// (with per-codepoint widths). Op ids are allocated from `ctr0` upward;
// returns the number of ops emitted, or a negative error:
//   -1 pos out of bounds   -2 delete past end
i64 am_edit_splice(void* p, i64 ctr0, i64 pos, i64 ndel, const i32* cps,
                   const i32* widths, i64 ncp) {
  return splice_impl(*static_cast<Session*>(p), ctr0, pos, ndel, cps, widths,
                     ncp);
}

// Bulk splice: `n_edits` edits, the i-th inserting
// cps[text_off[i] .. text_off[i+1]) at pos[i] after deleting ndel[i].
// With `clamp`, positions/deletes are clamped to the live length (the
// edit-trace replay convention). The whole loop runs native — this is
// the bulk-ingest path. Returns total ops emitted or a negative error.
i64 am_edit_splice_batch(void* p, i64 ctr0, const i64* pos, const i64* ndel,
                         const i64* text_off, const i32* cps,
                         const i32* widths, i64 n_edits, u8 clamp) {
  Session& s = *static_cast<Session*>(p);
  i64 total = 0;
  for (i64 i = 0; i < n_edits; i++) {
    i64 p_i = pos[i];
    i64 d_i = ndel[i];
    if (clamp) {
      if (p_i > s.total_width) p_i = s.total_width;
      if (d_i > s.total_width - p_i) d_i = s.total_width - p_i;
    }
    i64 r = splice_impl(s, ctr0 + total, p_i, d_i, cps + text_off[i],
                        widths + text_off[i], text_off[i + 1] - text_off[i]);
    if (r < 0) return r;
    total += r;
  }
  return total;
}

// Export emitted ops [start, count) in id order. Arrays must hold
// (op_count - start) rows. Returns rows written.
i64 am_edit_export(void* p, i64 start, i64* ids, i64* elem_refs, i64* preds,
                   i32* cps, i32* widths, u8* is_del) {
  Session& s = *static_cast<Session*>(p);
  if (start < 0 || (size_t)start > s.ops.size()) return -1;
  i64 w = 0;
  for (size_t i = (size_t)start; i < s.ops.size(); i++, w++) {
    const EOp& o = s.ops[i];
    ids[w] = o.id;
    elem_refs[w] = o.elem_ref;
    preds[w] = o.pred;
    cps[w] = o.cp;
    widths[w] = o.width;
    is_del[w] = o.is_del;
  }
  return w;
}

// Export the CURRENT visible element ids in document order (drain /
// debugging). Returns element count; caps at `cap`.
i64 am_edit_order(void* p, i64* out_ids, i64 cap) {
  Session& s = *static_cast<Session*>(p);
  i64 n = 0;
  for (i32 c = s.head; c != NONE; c = s.elems[c].next) {
    if (n < cap) out_ids[n] = s.elems[c].id;
    n++;
  }
  return n;
}

}  // extern "C"
