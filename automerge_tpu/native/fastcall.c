/* CPython fast-call shim for the per-edit hot path.
 *
 * ctypes costs ~1us per foreign call (argument marshalling through
 * libffi); a METH_FASTCALL extension entry is ~10x cheaper and can read
 * the codepoints straight out of the PyUnicode object instead of round-
 * tripping through numpy. This is the difference between the per-edit
 * replay API meeting the reference's transaction-replay throughput
 * (rust/edit-trace/benches/main.rs) and losing to it on call overhead.
 *
 * The session library (session.cpp, built into the codecs .so) is
 * reached through a function pointer installed by setup() — the address
 * comes from the ctypes CDLL that already loaded it, so there is exactly
 * one copy of the session code and no link-time coupling.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <stdlib.h>

typedef int64_t i64;
typedef int32_t i32;

typedef i64 (*splice_fn_t)(void *, i64, i64, i64, const i32 *, const i32 *,
                           i64);
typedef i64 (*map_put_fn_t)(void *, i64, const char *, i64, i32, i64, double,
                            const uint8_t *, i64);

static splice_fn_t g_splice = NULL;
static map_put_fn_t g_map_put = NULL;

static PyObject *setup(PyObject *self, PyObject *args) {
  unsigned long long addr;
  if (!PyArg_ParseTuple(args, "K", &addr)) return NULL;
  g_splice = (splice_fn_t)(uintptr_t)addr;
  Py_RETURN_NONE;
}

static PyObject *setup_map(PyObject *self, PyObject *args) {
  unsigned long long addr;
  if (!PyArg_ParseTuple(args, "K", &addr)) return NULL;
  g_map_put = (map_put_fn_t)(uintptr_t)addr;
  Py_RETURN_NONE;
}

/* splice(handle:int, ctr0:int, pos:int, ndel:int, text:str, enc:int) -> int
 *
 * enc selects the width unit: 0 = unicode codepoints (width 1),
 * 1 = utf-8 bytes, 2 = utf-16 code units (types.get_text_encoding).
 * Returns ops emitted, or the session's negative error code (the caller
 * maps it to the same exception the ctypes path raises). */
static PyObject *splice(PyObject *self, PyObject *const *args,
                        Py_ssize_t nargs) {
  if (nargs != 6) {
    PyErr_SetString(PyExc_TypeError, "splice expects 6 arguments");
    return NULL;
  }
  if (g_splice == NULL) {
    PyErr_SetString(PyExc_RuntimeError, "fastcall.setup() not called");
    return NULL;
  }
  void *h = (void *)(uintptr_t)PyLong_AsUnsignedLongLong(args[0]);
  i64 ctr0 = PyLong_AsLongLong(args[1]);
  i64 pos = PyLong_AsLongLong(args[2]);
  i64 ndel = PyLong_AsLongLong(args[3]);
  PyObject *text = args[4];
  long enc = PyLong_AsLong(args[5]);
  if (PyErr_Occurred()) return NULL;
  if (!PyUnicode_Check(text)) {
    PyErr_SetString(PyExc_TypeError, "splice text must be str");
    return NULL;
  }
  Py_ssize_t nt = PyUnicode_GET_LENGTH(text);
  i32 stack_cp[128];
  i32 stack_w[128];
  i32 *cp = stack_cp, *w = stack_w;
  if (nt > 128) {
    cp = (i32 *)malloc(sizeof(i32) * (size_t)nt * 2);
    if (cp == NULL) return PyErr_NoMemory();
    w = cp + nt;
  }
  const int kind = PyUnicode_KIND(text);
  const void *data = PyUnicode_DATA(text);
  for (Py_ssize_t i = 0; i < nt; i++) {
    Py_UCS4 c = PyUnicode_READ(kind, data, i);
    cp[i] = (i32)c;
    w[i] = enc == 1 ? 1 + (c > 0x7F) + (c > 0x7FF) + (c > 0xFFFF)
           : enc == 2 ? 1 + (c > 0xFFFF)
                      : 1;
  }
  i64 n = g_splice(h, ctr0, pos, ndel, cp, w, nt);
  if (cp != stack_cp) free(cp);
  return PyLong_FromLongLong(n);
}

/* map_put(handle:int, ctr:int, key:str, value) -> int
 *
 * The per-op map hot path: dispatches the Python value to the session's
 * column payload form (value_meta type code + raw bytes) without building
 * a ScalarValue, then records the op natively (map_session.cpp am_map_put:
 * pred = the key's current winner). Returns ops emitted (1), or -3 when
 * the key/value shape isn't session-eligible (empty key, non-str key,
 * big int, exotic type) — the caller falls back to the generic path,
 * which raises the proper typed errors. */
static PyObject *map_put(PyObject *self, PyObject *const *args,
                         Py_ssize_t nargs) {
  if (nargs != 4) {
    PyErr_SetString(PyExc_TypeError, "map_put expects 4 arguments");
    return NULL;
  }
  if (g_map_put == NULL) {
    PyErr_SetString(PyExc_RuntimeError, "fastcall.setup_map() not called");
    return NULL;
  }
  void *h = (void *)(uintptr_t)PyLong_AsUnsignedLongLong(args[0]);
  i64 ctr = PyLong_AsLongLong(args[1]);
  if (PyErr_Occurred()) return NULL;
  PyObject *key = args[2];
  PyObject *val = args[3];
  if (!PyUnicode_Check(key)) return PyLong_FromLong(-3);
  Py_ssize_t klen;
  const char *kbuf = PyUnicode_AsUTF8AndSize(key, &klen);
  if (kbuf == NULL) return NULL;
  if (klen == 0) return PyLong_FromLong(-3); /* empty key: python path raises */

  i32 code;
  i64 ival = 0;
  double fval = 0.0;
  const uint8_t *raw = NULL;
  i64 raw_len = 0;
  if (val == Py_None) {
    code = 0; /* null */
  } else if (PyBool_Check(val)) {
    code = (val == Py_True) ? 2 : 1;
  } else if (PyLong_Check(val)) {
    int overflow = 0;
    ival = PyLong_AsLongLongAndOverflow(val, &overflow);
    if (overflow || (ival == -1 && PyErr_Occurred())) {
      PyErr_Clear();
      return PyLong_FromLong(-3); /* bigint: python path */
    }
    code = 4; /* int (sleb) — matches ScalarValue.from_py(int) */
  } else if (PyFloat_Check(val)) {
    fval = PyFloat_AS_DOUBLE(val);
    code = 5;
  } else if (PyUnicode_Check(val)) {
    Py_ssize_t n;
    raw = (const uint8_t *)PyUnicode_AsUTF8AndSize(val, &n);
    if (raw == NULL) return NULL;
    raw_len = n;
    code = 6;
  } else if (PyBytes_Check(val)) {
    raw = (const uint8_t *)PyBytes_AS_STRING(val);
    raw_len = PyBytes_GET_SIZE(val);
    code = 7;
  } else {
    return PyLong_FromLong(-3); /* Counter/ScalarValue/objects: python path */
  }
  i64 n = g_map_put(h, ctr, kbuf, (i64)klen, code, ival, fval, raw, raw_len);
  return PyLong_FromLongLong(n);
}

static PyMethodDef methods[] = {
    {"setup", setup, METH_VARARGS, "Install the am_edit_splice address."},
    {"setup_map", setup_map, METH_VARARGS, "Install the am_map_put address."},
    {"splice", (PyCFunction)(void (*)(void))splice, METH_FASTCALL,
     "splice(handle, ctr0, pos, ndel, text, enc) -> ops emitted"},
    {"map_put", (PyCFunction)(void (*)(void))map_put, METH_FASTCALL,
     "map_put(handle, ctr, key, value) -> ops emitted"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef module = {PyModuleDef_HEAD_INIT, "am_fastcall",
                                    NULL, -1, methods};

PyMODINIT_FUNC PyInit_am_fastcall(void) { return PyModule_Create(&module); }
