// Host columnar merge: the native engine behind ops/merge.py merge_columns.
//
// Computes exactly what the jax kernel (ops/merge.py resolve_state +
// linearization) computes — succ resolution, visibility, per-key winners,
// RGA document order, per-object stats — from the same padded int32
// columns, but as O(n) linear passes on the host:
//
//   * succ resolution is one scatter loop over the pred stream (the
//     batched ``add_succ``, reference: rust/automerge/src/op_set.rs:194-203)
//   * per-key winner groups need NO sort: a sequence run's group id is the
//     run-head insert row itself (rows are Lamport-ranked by construction,
//     ops/oplog.py), so seq groups are a dense array indexed by row; map
//     groups go through a dense (obj x prop) table when small, else an
//     open-addressing hash
//   * sibling lists build by ascending-row prepend (descending Lamport =
//     descending row, reference: query/insert.rs tie-breaking), then the
//     existing native preorder walk (codecs.cpp am_preorder_index) ranks
//     document order
//
// Remote accelerators behind a thin link are round-trip-bound; below a
// size threshold this engine beats the device end to end (see
// merge_columns engine selection). Same columns in, same arrays out.

#include <chrono>
#include <climits>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

extern "C" long long am_preorder_index(const int32_t* first_child,
                                       const int32_t* next_sib,
                                       const int32_t* parent, int64_t P,
                                       int64_t N, int32_t* out);

namespace {

constexpr int32_t kPadAction = 15;
constexpr int32_t kDelete = 3;
constexpr int32_t kIncrement = 5;
constexpr int32_t kMark = 7;
constexpr int32_t kPut = 1;
constexpr int32_t kTagCounter = 8;
constexpr int32_t kElemHead = -1;
constexpr int32_t kElemMissing = -3;
constexpr int32_t kNone = -1;

struct Group {
  int32_t win = kNone;   // max visible row in the group
  int32_t cnt = 0;       // visible rows in the group
};

// Open-addressing (linear probe) map group table for the rare case where
// the dense (obj x prop) matrix would be too large.
struct MapHash {
  std::vector<uint64_t> keys;
  std::vector<int32_t> slot;
  std::vector<Group> groups;
  uint64_t mask;

  explicit MapHash(int64_t n) {
    uint64_t cap = 64;
    while (cap < (uint64_t)(2 * n)) cap <<= 1;
    keys.assign(cap, UINT64_MAX);
    slot.assign(cap, -1);
    mask = cap - 1;
  }
  Group* get(uint64_t key) {
    uint64_t h = (key * 0x9E3779B97F4A7C15ull) & mask;
    for (;;) {
      if (keys[h] == key) return &groups[slot[h]];
      if (keys[h] == UINT64_MAX) {
        keys[h] = key;
        slot[h] = (int32_t)groups.size();
        groups.emplace_back();
        return &groups.back();
      }
      h = (h + 1) & mask;
    }
  }
};

}  // namespace

extern "C" {

// All row arrays have length P (padded capacity; pad rows carry
// action == 15). pred arrays have length Q. Object-stat outputs have
// length n_objs + 2. first_child / next_sib are node space (2P + 3, as in
// ops/merge.py: elements [0,P), object roots [P,2P+2), sentinel).
// ``want_elem_index`` gates the preorder walk (the only random-access
// pass) — callers whose fetch excludes elem_index (historical views)
// skip it; elem_index is then left all -1.
// Returns 0, or -1 on a cyclic element structure.
long long am_merge_cols(
    const int32_t* action, const uint8_t* insert, const int32_t* prop,
    const int32_t* elem_ref, const int32_t* obj_dense,
    const int32_t* value_tag, const int32_t* value_i32, const int32_t* width,
    const uint8_t* covered, int64_t P, const int32_t* pred_src,
    const int32_t* pred_tgt, int64_t Q, int64_t n_objs,
    // outputs
    uint8_t* visible, int32_t* counter_inc, int32_t* winner,
    int32_t* conflicts, int32_t* succ_count, int32_t* inc_count,
    int32_t* first_child, int32_t* next_sib, int32_t* parent_row,
    uint8_t* is_elem, int32_t* obj_vis_len, int32_t* obj_text_width,
    int32_t* elem_index, int32_t want_elem_index) {
  const int64_t N = 2 * P + 3;
  const int32_t S = (int32_t)(N - 1);

  const bool timing = getenv("AM_MERGE_TIMING") != nullptr;
  auto now_s = [] {
    return std::chrono::duration<double>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  };
  double t0 = timing ? now_s() : 0.0;
  auto tick = [&](const char* name) {
    if (!timing) return;
    const double t1 = now_s();
    fprintf(stderr, "merge %-10s %.4fs\n", name, t1 - t0);
    t0 = t1;
  };

  // --- 1. succ resolution (pred scatter) --------------------------------
  std::memset(succ_count, 0, P * sizeof(int32_t));
  std::memset(inc_count, 0, P * sizeof(int32_t));
  std::memset(counter_inc, 0, P * sizeof(int32_t));
  for (int64_t e = 0; e < Q; e++) {
    const int32_t tgt = pred_tgt[e];
    if (tgt < 0) continue;
    const int32_t src = pred_src[e];
    if (!covered[src]) continue;
    if (action[src] == kIncrement) {
      inc_count[tgt]++;
      counter_inc[tgt] += value_i32[src];
    } else {
      succ_count[tgt]++;
    }
  }

  tick("succ");
  // --- 2. visibility (types.rs:712-744) ---------------------------------
  for (int64_t i = 0; i < P; i++) {
    const int32_t a = action[i];
    if (a == kPadAction || !covered[i] || a == kDelete || a == kIncrement ||
        a == kMark) {
      visible[i] = 0;
      continue;
    }
    const bool is_counter = (a == kPut) && (value_tag[i] == kTagCounter);
    visible[i] =
        (is_counter ? succ_count[i] == 0
                    : (succ_count[i] + inc_count[i]) == 0)
            ? 1
            : 0;
  }

  tick("visible");
  // --- 3. per-key winners ------------------------------------------------
  // seq groups: dense by run-head row; HEAD / missing targets get two
  // per-object slots (they group by (obj, sentinel key) on the device too)
  std::vector<Group> run(P);
  const int64_t n_objs2 = n_objs + 2;
  std::vector<Group> head_g(n_objs2), miss_g(n_objs2);
  // map groups: dense (obj x prop) when small, hash otherwise
  int64_t n_props = 0;
  for (int64_t i = 0; i < P; i++)
    if (action[i] != kPadAction && prop[i] >= n_props) n_props = prop[i] + 1;
  const bool dense_maps =
      n_props == 0 || n_objs2 <= (4 * P + 65536) / n_props;
  std::vector<Group> map_dense(dense_maps ? n_objs2 * n_props : 0);
  MapHash map_hash(dense_maps ? 1 : P);

  auto group_of = [&](int64_t i) -> Group* {
    if (prop[i] >= 0) {
      if (dense_maps) return &map_dense[(int64_t)obj_dense[i] * n_props + prop[i]];
      return map_hash.get(((uint64_t)obj_dense[i] << 32) | (uint32_t)prop[i]);
    }
    const int32_t er = elem_ref[i];
    const int32_t r = insert[i] ? (int32_t)i : er;
    if (r >= 0) return &run[r];
    return er == kElemHead ? &head_g[obj_dense[i]] : &miss_g[obj_dense[i]];
  };

  for (int64_t i = 0; i < P; i++) {
    if (action[i] == kPadAction) continue;
    if (!visible[i]) continue;
    Group* g = group_of(i);
    g->win = (int32_t)i;  // rows ascend: the last visible row wins
    g->cnt++;
  }
  for (int64_t i = 0; i < P; i++) {
    if (action[i] == kPadAction) {
      winner[i] = kNone;
      conflicts[i] = 0;
      continue;
    }
    const Group* g = group_of(i);
    winner[i] = g->win;
    conflicts[i] = g->cnt;
  }

  tick("winners");
  // --- 4. RGA linearization ----------------------------------------------
  // parent chain + sibling lists; ascending-row prepend leaves each child
  // list in descending row (= descending Lamport) order.
  // (Kept as separate streaming passes: fusing them into the winners pass
  // mixes three access patterns per iteration and measured SLOWER.)
  std::memset(first_child, 0xFF, (size_t)N * sizeof(int32_t));  // kNone
  std::memset(next_sib, 0xFF, (size_t)N * sizeof(int32_t));
  for (int64_t i = 0; i < P; i++) {
    const bool el = insert[i] && action[i] != kPadAction;
    is_elem[i] = el ? 1 : 0;
    if (!el) {
      parent_row[i] = S;
      continue;
    }
    const int32_t er = elem_ref[i];
    const int32_t p = er == kElemHead ? (int32_t)(P + obj_dense[i])
                                      : (er >= 0 ? er : S);
    parent_row[i] = p;
    next_sib[i] = first_child[p];
    first_child[p] = (int32_t)i;
  }
  if (want_elem_index) {
    if (am_preorder_index(first_child, next_sib, parent_row, P, N,
                          elem_index) < 0)
      return -1;
    for (int64_t i = 0; i < P; i++)
      if (!is_elem[i]) elem_index[i] = kNone;
  } else {
    for (int64_t i = 0; i < P; i++) elem_index[i] = kNone;
  }

  tick("linearize");
  // --- per-object stats ---------------------------------------------------
  std::memset(obj_vis_len, 0, n_objs2 * sizeof(int32_t));
  std::memset(obj_text_width, 0, n_objs2 * sizeof(int32_t));
  for (int64_t i = 0; i < P; i++) {
    if (!is_elem[i] || winner[i] < 0) continue;
    const int32_t o = obj_dense[i];
    if (o >= n_objs2) continue;  // padded sentinel object
    obj_vis_len[o]++;
    obj_text_width[o] += width[winner[i]];
  }
  tick("stats");
  return 0;
}

// String-table RLE encode: the encode counterpart of codecs.cpp
// am_rle_decode_batch_strtab. ids[i] is -1 (null) or an index into the
// string table (tab_off/tab_len into tab_buf, utf-8 payloads); equal ids
// are equal strings (tables are interned). Run/literal/null-run framing is
// byte-identical to the Python RleEncoder("str") / am_rle_encode_i64:
// sleb(count)+value for runs, sleb(-k)+k values for literals, sleb(0)+
// uleb(n) for null runs; an all-null column encodes to zero bytes.
// Returns bytes written, or -1 on output overflow.
long long am_rle_encode_strtab(const int64_t* ids, int64_t n,
                               const int64_t* tab_off, const int64_t* tab_len,
                               const uint8_t* tab_buf, uint8_t* out,
                               int64_t out_cap) {
  int64_t w = 0;
  bool ok = true;
  auto uleb = [&](uint64_t v) {
    do {
      uint8_t b = v & 0x7F;
      v >>= 7;
      if (v) b |= 0x80;
      if (w >= out_cap) {
        ok = false;
        return;
      }
      out[w++] = b;
    } while (v && ok);
  };
  auto sleb = [&](int64_t v) {
    for (;;) {
      uint8_t b = v & 0x7F;
      v >>= 7;
      const bool done = (v == 0 && !(b & 0x40)) || (v == -1 && (b & 0x40));
      if (!done) b |= 0x80;
      if (w >= out_cap) {
        ok = false;
        return;
      }
      out[w++] = b;
      if (done) return;
    }
  };
  auto value = [&](int64_t id) {
    const int64_t len = tab_len[id];
    uleb((uint64_t)len);
    if (w + len > out_cap) {
      ok = false;
      return;
    }
    std::memcpy(out + w, tab_buf + tab_off[id], (size_t)len);
    w += len;
  };
  int64_t i = 0;
  while (i < n && ok) {
    if (ids[i] < 0) {  // null run
      int64_t j = i;
      while (j < n && ids[j] < 0) j++;
      if (i == 0 && j == n) return 0;  // all-null: zero bytes
      sleb(0);
      uleb((uint64_t)(j - i));
      i = j;
      continue;
    }
    int64_t j = i + 1;
    while (j < n && ids[j] == ids[i]) j++;
    if (j - i >= 2) {  // value run
      sleb(j - i);
      value(ids[i]);
      i = j;
      continue;
    }
    // literal run: until a pair of equal values or a null
    const int64_t lit_start = i;
    for (;;) {
      if (j >= n || ids[j] < 0) break;
      if (ids[j] == ids[j - 1]) {
        j--;
        break;
      }
      j++;
    }
    sleb(-(j - lit_start));
    for (int64_t k = lit_start; k < j && ok; k++) value(ids[k]);
    i = j;
  }
  return ok ? (long long)w : -1;
}

// Sorted join: out[i] = position of q[i] in sorted[0..n) if present, else
// ``missing``. The extraction hot path resolves op-id references (elem /
// pred targets) against the Lamport-sorted id column with this. Packed op
// ids (counter << ACTOR_BITS | rank) are near-uniform over their value
// range in real logs, so a few interpolation probes narrow the window
// before the binary search — ~3-4 memory touches instead of log2(n) on a
// cold array. Degenerate distributions just fall through to binary
// search over the narrowed (or full) window. The query range splits
// across threads when the host has them.
long long am_join_rows_i64(const int64_t* sorted, int64_t n, const int64_t* q,
                           int64_t m, int32_t missing, int32_t* out) {
  // direct-mapped memo: real query streams are highly repetitive (RGA
  // anchors and typing chains reference a small working set of targets),
  // so most lookups resolve to one probe of a 64k-entry cache instead of
  // a search. The empty marker is INT64_MIN — no packed id reaches it, so
  // ANY query key (including 0, which both callers do pass) is safe.
  // Per-thread tables — a shared memo's two-field entries would tear
  // under concurrent writes — and only for ranges big enough to amortize
  // the table's zero-init (small incremental joins skip it).
  constexpr int64_t kCacheBits = 16;
  constexpr int64_t kEmpty = INT64_MIN;
  // gate on the TOTAL query count (a per-chunk gate would disable the
  // memo for mid-size joins exactly when the thread split is active)
  const bool use_memo = m >= (int64_t)1 << (kCacheBits - 2);
  auto run = [&](int64_t lo, int64_t hi) {
    std::vector<int64_t> memo_key;
    std::vector<int32_t> memo_val;
    if (use_memo) {
      memo_key.assign((size_t)1 << kCacheBits, kEmpty);
      memo_val.assign((size_t)1 << kCacheBits, 0);
    }
    for (int64_t i = lo; i < hi; i++) {
      const int64_t key = q[i];
      size_t slot = 0;
      // key == kEmpty must never consult the memo: a never-written slot
      // would false-hit on the empty marker
      if (use_memo && key != kEmpty) {
        slot = (size_t)((uint64_t)(key * 0x9E3779B97F4A7C15ull) >>
                        (64 - kCacheBits));
        if (memo_key[slot] == key) {
          out[i] = memo_val[slot];
          continue;
        }
      }
      int64_t a = 0, b = n;
      // interpolation steps keep the lower_bound invariant (answer in
      // [a, b]): p is clamped into [a, b-1], then the same narrowing rule
      // as the binary step applies. ~1.7x over plain binary here
      // (lockstep-prefetch and branchless variants measured WORSE on this
      // host — see round-3 notes).
      for (int probe = 0; probe < 4 && b - a > 64; probe++) {
        const int64_t va = sorted[a], vb = sorted[b - 1];
        if (vb <= va || key <= va || key >= vb) break;
        int64_t p = a + (int64_t)((double)(key - va) / (double)(vb - va) *
                                  (double)(b - 1 - a));
        if (p < a) p = a;
        if (p > b - 1) p = b - 1;
        if (sorted[p] < key)
          a = p + 1;
        else
          b = p;
      }
      while (a < b) {
        const int64_t mid = (a + b) >> 1;
        if (sorted[mid] < key)
          a = mid + 1;
        else
          b = mid;
      }
      const int32_t r = (a < n && sorted[a] == key) ? (int32_t)a : missing;
      out[i] = r;
      if (use_memo && key != kEmpty) {
        memo_key[slot] = key;
        memo_val[slot] = r;
      }
    }
  };
  const unsigned hw = std::thread::hardware_concurrency();
  const int64_t nt =
      m >= 16384 ? (int64_t)(hw > 8 ? 8 : (hw ? hw : 1)) : 1;
  if (nt <= 1) {
    run(0, m);
    return 0;
  }
  std::vector<std::thread> ts;
  const int64_t step = (m + nt - 1) / nt;
  for (int64_t t = 0; t < nt; t++) {
    const int64_t lo = t * step, hi = lo + step < m ? lo + step : m;
    if (lo >= hi) break;
    ts.emplace_back(run, lo, hi);
  }
  for (auto& t : ts) t.join();
  return 0;
}

}  // extern "C"
