// Sequential per-op apply engine: the native baseline the batched device
// kernel is measured against, and the fast host apply path.
//
// This is the reference's apply_changes hot loop re-expressed in C++
// (reference: rust/automerge/src/automerge.rs:1258-1280 insert_op =
// seek -> add_succ -> insert; op_tree.rs:212-239 forward lamport scan;
// op_set.rs:194-253). Ops arrive flattened in change-apply (causal) order
// with ids packed as (counter << 20 | actor_rank) so int64 comparison ==
// lamport_cmp (types.rs:517-521, actor ranks are byte-sorted).
//
// Data layout: per-sequence-object doubly-linked element pool (index-based,
// cache-dense), a global id -> record hash for pred targeting, per-element
// update chains and per-(object,prop) map runs kept in ascending lamport
// order. Visibility: op visible iff no non-increment successor (counters)
// / no successor at all (everything else) — types.rs:712-744.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

using i64 = long long;
using i32 = int32_t;
using u8 = uint8_t;

constexpr i32 ACT_DELETE = 3;
constexpr i32 ACT_INCREMENT = 5;
constexpr i32 ACT_MARK_BEGIN = 7;
constexpr i32 ACT_MARK_END = 8;  // storage expand bit distinguishes; unused here
constexpr i32 NONE = -1;

inline bool is_make(i32 action) {
  return action == 0 || action == 2 || action == 4 || action == 6;
}

struct Rec {        // one stored op (element insert, map put, or seq update)
  i64 id;
  i32 row;          // index into the input arrays (value identity)
  i32 succ = 0;     // non-increment successors
  i32 inc = 0;      // increment successors
  u8 is_counter;
  u8 alive = 1;
};

struct Elem {       // a sequence element: its insert op + update chain
  Rec op;
  i32 prev = NONE, next = NONE;
  i32 upd_head = NONE;  // first update (ascending id), chained via upd_next
};

struct Upd {
  Rec op;
  i32 next = NONE;
};

struct SeqObj {
  i32 head = NONE;  // first element slot (no sentinel; head/prev==NONE)
  i32 tail = NONE;
};

struct MapRun {     // ops at one (object, prop), ascending lamport
  std::vector<i32> upds;  // indices into upd pool
};

struct Engine {
  std::vector<Elem> elems;
  std::vector<Upd> upds;
  std::vector<SeqObj> seqs;
  // object id -> (is_map << 31) | index into seqs / maps
  std::unordered_map<i64, i64> objects;
  std::vector<std::unordered_map<i64, MapRun>> maps;  // keyed by prop idx
  // op id -> record location: (kind<<32)|slot, kind 0=elem 1=upd
  std::unordered_map<i64, i64> by_id;

  bool visible(const Rec& r) const {
    return r.is_counter ? r.succ == 0 : (r.succ == 0 && r.inc == 0);
  }
};

inline void mark_pred(Engine& e, i64 pred_id, bool inc) {
  auto it = e.by_id.find(pred_id);
  if (it == e.by_id.end()) return;  // pred outside this log (partial apply)
  i64 loc = it->second;
  Rec& r = (loc >> 32) ? e.upds[(i32)loc].op : e.elems[(i32)loc].op;
  if (inc)
    r.inc++;
  else
    r.succ++;
}

// Runs the sequential apply over all ops; returns 0 or a negative error.
i64 engine_apply(Engine& e, const i64* id, const i64* obj, const i64* elem,
                 const i32* prop, const i32* action, const u8* insert,
                 const u8* is_counter, const i64* pred_off,
                 const i64* pred_flat, i64 n_ops) {
  e.elems.reserve((size_t)n_ops);
  e.seqs.reserve(1024);
  e.maps.emplace_back();  // root is a map
  e.objects.emplace(0, (1LL << 31) | 0);
  e.by_id.reserve((size_t)n_ops * 2);

  for (i64 i = 0; i < n_ops; i++) {
    i32 act = action[i];
    if (is_make(act)) {
      // register the object (map/table -> map store, list/text -> seq)
      if (act == 0 || act == 6) {
        e.objects.emplace(id[i], (1LL << 31) | (i64)e.maps.size());
        e.maps.emplace_back();
      } else {
        e.objects.emplace(id[i], (i64)e.seqs.size());
        e.seqs.emplace_back();
      }
    }
    auto oit = e.objects.find(obj[i]);
    if (oit == e.objects.end()) return -2;  // op on unknown object
    bool obj_is_map = (oit->second >> 31) != 0;
    i32 oslot = (i32)(oit->second & 0x7fffffff);
    bool is_inc = act == ACT_INCREMENT;

    // add_succ on every pred (op_set.rs:194-203, batched in the kernel)
    for (i64 p = pred_off[i]; p < pred_off[i + 1]; p++)
      mark_pred(e, pred_flat[p], is_inc);

    if (obj_is_map) {
      if (act == ACT_DELETE || is_inc) continue;  // never stored
      Upd u;
      u.op = Rec{id[i], (i32)i, 0, 0, is_counter[i], 1};
      i32 slot = (i32)e.upds.size();
      e.upds.push_back(u);
      e.by_id.emplace(id[i], (1LL << 32) | slot);
      auto& run = e.maps[oslot][prop[i]].upds;
      // ascending lamport insert (runs are tiny: concurrent writers only)
      size_t pos = run.size();
      while (pos > 0 && id[i] < e.upds[run[pos - 1]].op.id) pos--;
      run.insert(run.begin() + pos, slot);
      continue;
    }

    SeqObj& so = e.seqs[oslot];
    if (insert[i]) {
      // seek: ref element, then skip siblings with greater lamport id
      // (query/opid.rs SimpleOpIdSearch; op_tree.rs:212-239)
      i32 after;
      i32 prev;
      if (elem[i] == 0) {  // HEAD
        prev = NONE;
        after = so.head;
      } else {
        auto rit = e.by_id.find(elem[i]);
        if (rit == e.by_id.end() || (rit->second >> 32)) return -3;
        prev = (i32)rit->second;
        after = e.elems[prev].next;
      }
      while (after != NONE && id[i] < e.elems[after].op.id) {
        prev = after;
        after = e.elems[after].next;
      }
      Elem el;
      el.op = Rec{id[i], (i32)i, 0, 0, is_counter[i], 1};
      el.prev = prev;
      el.next = after;
      i32 slot = (i32)e.elems.size();
      e.elems.push_back(el);
      if (prev == NONE)
        so.head = slot;
      else
        e.elems[prev].next = slot;
      if (after == NONE)
        so.tail = slot;
      else
        e.elems[after].prev = slot;
      e.by_id.emplace(id[i], (i64)slot);
    } else {
      if (act == ACT_DELETE || is_inc) continue;  // preds already marked
      if (act == ACT_MARK_BEGIN || act == ACT_MARK_END) continue;
      auto rit = e.by_id.find(elem[i]);
      if (rit == e.by_id.end() || (rit->second >> 32)) return -4;
      i32 eslot = (i32)rit->second;
      Upd u;
      u.op = Rec{id[i], (i32)i, 0, 0, is_counter[i], 1};
      i32 slot = (i32)e.upds.size();
      e.upds.push_back(u);
      e.by_id.emplace(id[i], (1LL << 32) | slot);
      // ascending-id insert into the element's update chain
      i32* link = &e.elems[eslot].upd_head;
      while (*link != NONE && e.upds[*link].op.id < id[i])
        link = &e.upds[*link].next;
      e.upds[slot].next = *link;
      *link = slot;
    }
  }
  return 0;
}

}  // namespace

extern "C" {

// Applies n_ops ops; returns the number of visible winner rows written for
// query_obj (a sequence object), or a negative error code.
//   ops columns (length n_ops, change-apply order):
//     id, obj (0 = root), elem (0 = HEAD, only for seq ops), prop (-1 for
//     seq ops), action, insert, is_counter
//   preds as CSR: pred_off (n_ops + 1), pred_flat (pred_off[n_ops])
//   out_rows: winner row per visible element of query_obj, document order
i64 am_seq_apply(const i64* id, const i64* obj, const i64* elem,
                 const i32* prop, const i32* action, const u8* insert,
                 const u8* is_counter, const i64* pred_off,
                 const i64* pred_flat, i64 n_ops, i64 query_obj,
                 i32* out_rows, i64 out_cap) {
  Engine e;
  i64 rc = engine_apply(e, id, obj, elem, prop, action, insert, is_counter,
                        pred_off, pred_flat, n_ops);
  if (rc < 0) return rc;

  // readback: visible winner rows of query_obj in document order
  auto qit = e.objects.find(query_obj);
  if (qit == e.objects.end() || (qit->second >> 31)) return -5;
  SeqObj& so = e.seqs[(i32)(qit->second & 0x7fffffff)];
  i64 n_out = 0;
  for (i32 s = so.head; s != NONE; s = e.elems[s].next) {
    const Rec* win = nullptr;
    if (e.visible(e.elems[s].op)) win = &e.elems[s].op;
    for (i32 u = e.elems[s].upd_head; u != NONE; u = e.upds[u].next)
      if (e.visible(e.upds[u].op)) win = &e.upds[u].op;  // later id wins
    if (win != nullptr) {
      if (n_out < out_cap) out_rows[n_out] = win->row;
      n_out++;
    }
  }
  return n_out;
}

// Applies n_ops ops and exports the full RGA element order of EVERY
// sequence object: the host op-store bulk loader rebuilds its linked
// structures from this (everything else — succ lists, visibility, map
// runs — is recomputed vectorized on the host; only element order needs
// the sequential integrate).
//   out_obj_key[k]            packed object id of the k-th seq object
//   out_obj_off[k], [k+1]     its slice of out_elem_rows
//   out_elem_rows             element insert-op rows, document order,
//                             INCLUDING invisible (tombstoned) elements
// Returns the number of sequence objects, or a negative error code.
// elem_cap must be >= the number of insert ops; obj_cap >= seq obj count.
i64 am_seq_apply_export(const i64* id, const i64* obj, const i64* elem,
                        const i32* prop, const i32* action, const u8* insert,
                        const u8* is_counter, const i64* pred_off,
                        const i64* pred_flat, i64 n_ops, i64* out_obj_key,
                        i64* out_obj_off, i64 obj_cap, i32* out_elem_rows,
                        i64 elem_cap) {
  Engine e;
  i64 rc = engine_apply(e, id, obj, elem, prop, action, insert, is_counter,
                        pred_off, pred_flat, n_ops);
  if (rc < 0) return rc;

  // objects in registration order (deterministic): walk the id map is
  // unordered, so re-derive seq object keys by scanning make ops + root
  std::vector<std::pair<i64, i32>> seq_objs;  // (packed key, seq slot)
  seq_objs.reserve(e.seqs.size());
  for (const auto& kv : e.objects)
    if (!(kv.second >> 31))
      seq_objs.emplace_back(kv.first, (i32)(kv.second & 0x7fffffff));
  std::sort(seq_objs.begin(), seq_objs.end());
  if ((i64)seq_objs.size() > obj_cap) return -6;

  i64 k = 0, w = 0;
  for (auto& [key, slot] : seq_objs) {
    out_obj_key[k] = key;
    out_obj_off[k] = w;
    for (i32 s = e.seqs[slot].head; s != NONE; s = e.elems[s].next) {
      if (w >= elem_cap) return -7;
      out_elem_rows[w++] = e.elems[s].op.row;
    }
    k++;
  }
  out_obj_off[k] = w;
  return k;
}

}  // extern "C"
