// Log assembly: per-change cached op columns -> final Lamport-ordered,
// reference-resolved device columns, in one native pass.
//
// This is the merge path's answer to the reference's per-op
// seek-and-insert loop (automerge.rs:1258-1280): instead of decoding the
// change chunks into a tree, each change keeps its decoded chunk-local
// column arrays (attached at commit time or on first decode), and a merge
// assembles N ops with
//   1. a counting sort over (counter, actor-rank) that exploits the runs
//      of CONSECUTIVE counters every change carries by construction
//      (ids are start_op..start_op+n-1), so Lamport ordering is O(N)
//      instead of O(N log N);
//   2. column gathers through the emit permutation (no intermediate
//      concatenation);
//   3. change-SPAN reference resolution: an op id (ctr, rank) is located
//      by binary search over the ~C-entry change table plus an inverse-
//      permutation lookup — not by joining against the N-row id column.
//      (C ~ 1k..10k entries stays L1/L2-resident; the old sorted join
//      walked a 376k-row array per query.)
//
// Returns 0 on success, 1 when the caller must recompute the object
// table host-side (an object id that is not a make op in this log —
// partial histories), negative on malformed input (caller falls back to
// the python paths, which report canonical errors).

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <vector>

namespace {
inline double now_s() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + 1e-9 * ts.tv_nsec;
}
}  // namespace

namespace {

constexpr int32_t ELEM_HEAD = -1;
constexpr int32_t ELEM_MAP = -2;
constexpr int32_t ELEM_MISSING = -3;
constexpr int32_t TAG_UNKNOWN = 10;

// make actions (object-creating ops; reference types.rs action indices
// 0/2/4/6) as a bitmask test
inline bool is_make_action(int32_t a) {
  return a >= 0 && a < 8 && ((1u << a) & 0b01010101u);
}

struct Span {
  int64_t key;      // rank << 43 | start_op  (rank < 2^20, ctr < 2^43)
  int64_t start;    // start_op
  int64_t n;        // ops in change
  int64_t row_off;  // concat-order row offset of the change
};

}  // namespace

extern "C" {

// col_ptrs layout per change (row-major, 18 entries):
//   0 action   i32[n]     1 obj_ctr  i64[n]   2 obj_actor i32[n]
//   3 obj_has  u8[n]      4 key_sid  i32[n]   5 elem_ctr  i64[n]
//   6 elem_actor i32[n]   7 insert   u8[n]    8 expand    u8[n]
//   9 vcode    i32[n]    10 vlen     i64[n]  11 voff      i64[n]
//  12 value_int i64[n]   13 width    i32[n]  14 mark_sid  i32[n]
//  15 pred_num i32[n]    16 pred_ctr i64[q]  17 pred_actor i32[q]
//  18 hot: 24-byte AoS record {elem_ctr i64, voff u32, vlen u32,
//     elem_actor i32, action u8, vcode u8, insert u8, pad} — the
//     gather-heavy columns interleaved so a permuted row read touches
//     one cache line, not seven per-change streams (and at 24B, 2.6
//     rows per line instead of 1.6)
//
// g_flags/g_vals (18 slots, indexed like the columns): globally-constant
// columns the caller proved identical across every change — the
// assembler FILLS those outputs sequentially and skips their gathers
// (real logs are dominated by such columns: one target object, no
// marks, constant widths/payloads). Slot semantics:
//   [1]=1: obj_key := g_vals[1] (already rank-translated), obj_dense
//          resolved once;  [4]=1: key_sid const -1 (prop := -1);
//   [4]=2: prop := g_vals[4] (global id), elem_ref := ELEM_MAP;
//   [7,8,9,10,12,13]: plain value fills; [11]: voff fill (only valid
//   when the value heap is empty); [14]: mark_idx := g_vals[14].
long long am_assemble_log(
    const int64_t* n_ops, const int64_t* q_ops, const int64_t* start_op,
    const int64_t* author_rank, const int64_t* tab_off,
    const int64_t* tab_size, const int64_t* prop_off,
    const int64_t* prop_size, const int64_t* mark_off,
    const int64_t* mark_size, const int64_t* raw_base,
    const int64_t* col_ptrs, int64_t n_changes, const int64_t* tab_all,
    const int32_t* prop_remap_all, const int32_t* mark_remap_all,
    int32_t actor_bits, const int64_t* g_flags, const int64_t* g_vals,
    // per-change constant shortcuts (see assemble.py _per_change_const):
    // c_obj_key[c] >= 0: every row of change c targets that packed object
    // (-1 = varies); c_sid_arr[c] == -1: all rows seq-keyed, >= 0: one
    // global map prop, -2 = varies
    const int64_t* c_obj_key, const int64_t* c_sid_arr,
    // outputs, length N
    int64_t* id_key, int64_t* obj_key, int32_t* prop, int32_t* action,
    uint8_t* insert, uint8_t* expand, int32_t* value_tag,
    int64_t* value_int, int32_t* width, int32_t* mark_idx, int32_t* vcode,
    int64_t* voff, int64_t* vlen, int32_t* elem_ref, int32_t* obj_dense,
    int64_t n_total,
    // outputs, length Q
    int32_t* pred_src, int32_t* pred_tgt, int64_t q_total,
    // obj_table capacity must be >= #make ops + 1; out_meta[0] = n_objs
    int64_t* obj_table, int64_t* out_meta) {
  const int64_t C = n_changes;
  const int64_t N = n_total;
  const int64_t AB = actor_bits;
  if (N == 0) {
    obj_table[0] = 0;
    out_meta[0] = 1;
    return 0;
  }

  const bool timing = getenv("AM_ASSEMBLE_TIMING") != nullptr;
  double t0 = timing ? now_s() : 0.0;
  auto tick = [&](const char* name) {
    if (!timing) return;
    const double t1 = now_s();
    fprintf(stderr, "assemble %-10s %.4fs\n", name, t1 - t0);
    t0 = t1;
  };
  auto cp = [&](int64_t c, int k) -> const void* {
    return (const void*)(uintptr_t)col_ptrs[c * 19 + k];
  };

  // concat-order row offsets + validation
  std::vector<int64_t> row_off(C + 1), pred_off(C + 1);
  int64_t min_ctr = INT64_MAX, max_ctr = INT64_MIN;
  {
    int64_t acc = 0, qacc = 0;
    for (int64_t c = 0; c < C; c++) {
      row_off[c] = acc;
      pred_off[c] = qacc;
      if (n_ops[c] < 0 || q_ops[c] < 0 || start_op[c] < 1) return -1;
      acc += n_ops[c];
      qacc += q_ops[c];
      if (n_ops[c]) {
        min_ctr = std::min(min_ctr, start_op[c]);
        max_ctr = std::max(max_ctr, start_op[c] + n_ops[c] - 1);
      }
      if (author_rank[c] < 0 || author_rank[c] >= ((int64_t)1 << AB))
        return -2;
    }
    row_off[C] = acc;
    pred_off[C] = qacc;
    if (acc != N || qacc != q_total) return -3;
  }

  // ---- 1. Lamport ordering ------------------------------------------------
  // src[j] = concat-order row that lands at sorted position j;
  // newrow[old] = sorted position of concat-order row `old`.
  std::vector<int32_t> src(N), newrow(N);
  std::vector<int32_t> src_c(N);  // owning change per sorted row
  const int64_t range = max_ctr - min_ctr + 1;
  // order changes by author rank so same-counter buckets fill in rank
  // order (ranks are unique per actor; one actor's changes never overlap
  // in counter range, so within a bucket each change appears once)
  std::vector<int32_t> by_rank(C);
  for (int64_t c = 0; c < C; c++) by_rank[c] = (int32_t)c;
  std::stable_sort(by_rank.begin(), by_rank.end(),
                   [&](int32_t a, int32_t b) {
                     return author_rank[a] < author_rank[b];
                   });
  if (range <= std::max<int64_t>(4 * N, 1 << 22)) {
    // counting sort over the counter range (the common, regular case);
    // i32 buckets halve the table's cache traffic (counts and positions
    // both fit: N < 2^31)
    // counts via an interval diff array (each change covers a consecutive
    // counter range): O(C + range) instead of O(N) scattered increments
    std::vector<int32_t> bucket(range + 1, 0);
    for (int64_t c = 0; c < C; c++) {
      if (!n_ops[c]) continue;
      bucket[start_op[c] - min_ctr]++;
      bucket[start_op[c] + n_ops[c] - min_ctr]--;
    }
    int32_t cover = 0, acc = 0;
    for (int64_t b = 0; b < range; b++) {
      cover += bucket[b];
      bucket[b] = acc;
      acc += cover;
    }
    if (range * C <= 8 * N && C > 64) {
      // many changes sharing a narrow counter range (the map+counter
      // fan-in shape: 10k actors x 1k ops over the same counters): the
      // per-change placement loop writes src at a C-change stride — one
      // cache miss per row over a multi-hundred-MB window. Place in
      // BLOCKS of changes instead: each (block, counter) pair touches a
      // contiguous src segment and a block-local newrow window, keeping
      // the working set L2-resident. Blocks run in rank order, so each
      // counter bucket still fills in rank order.
      constexpr int64_t BLK = 256;
      for (int64_t blk = 0; blk < C; blk += BLK) {
        const int64_t be = std::min(blk + BLK, C);
        for (int64_t b = 0; b < range; b++) {
          for (int64_t k = blk; k < be; k++) {
            const int64_t c = by_rank[k];
            const int64_t i = b - (start_op[c] - min_ctr);
            if (i < 0 || i >= n_ops[c]) continue;
            const int32_t pos = bucket[b]++;
            const int64_t base = row_off[c];
            src[pos] = (int32_t)(base + i);
            src_c[pos] = (int32_t)c;
            newrow[base + i] = pos;
          }
        }
      }
    } else {
      for (int64_t ci = 0; ci < C; ci++) {
        const int64_t c = by_rank[ci];
        const int64_t base = row_off[c], s0 = start_op[c] - min_ctr;
        for (int64_t i = 0; i < n_ops[c]; i++) {
          const int32_t pos = bucket[s0 + i]++;
          src[pos] = (int32_t)(base + i);
          src_c[pos] = (int32_t)c;
          newrow[base + i] = pos;
        }
      }
    }
  } else {
    // degenerate counter distribution: comparator sort on packed keys
    std::vector<int64_t> keys(N);
    for (int64_t c = 0; c < C; c++)
      for (int64_t i = 0; i < n_ops[c]; i++)
        keys[row_off[c] + i] =
            ((start_op[c] + i) << AB) | author_rank[c];
    std::vector<int32_t> owner(N);
    for (int64_t c = 0; c < C; c++)
      for (int64_t i = 0; i < n_ops[c]; i++)
        owner[row_off[c] + i] = (int32_t)c;
    for (int64_t j = 0; j < N; j++) src[j] = (int32_t)j;
    std::stable_sort(src.begin(), src.end(), [&](int32_t a, int32_t b) {
      return keys[a] < keys[b];
    });
    for (int64_t j = 0; j < N; j++) {
      newrow[src[j]] = (int32_t)j;
      src_c[j] = owner[src[j]];
    }
  }

  tick("sort");
  // ---- 2. span table for reference resolution -----------------------------
  std::vector<Span> spans;
  spans.reserve(C);
  for (int64_t c = 0; c < C; c++) {
    if (!n_ops[c]) continue;
    spans.push_back(Span{(author_rank[c] << 43) | start_op[c], start_op[c],
                         n_ops[c], row_off[c]});
  }
  std::sort(spans.begin(), spans.end(),
            [](const Span& a, const Span& b) { return a.key < b.key; });
  const int64_t S = (int64_t)spans.size();
  // resolve (ctr, rank) -> sorted row, -1 if not in this log. Reference
  // streams are extremely repetitive — RGA insert chains target the
  // author's own change and anchors/preds target the (few) base
  // changes — so a referencing-change fast path plus a last-span memo
  // resolves almost everything in O(1); the binary search is the rare
  // path.
  int64_t memo_span = -1;
  auto resolve2 = [&](int64_t ctr, int64_t rank, int64_t c_hint) -> int32_t {
    if (author_rank[c_hint] == rank && ctr >= start_op[c_hint] &&
        ctr < start_op[c_hint] + n_ops[c_hint])
      return newrow[row_off[c_hint] + (ctr - start_op[c_hint])];
    if (memo_span >= 0) {
      const Span& sp = spans[memo_span];
      if ((sp.key >> 43) == rank && ctr >= sp.start && ctr < sp.start + sp.n)
        return newrow[sp.row_off + (ctr - sp.start)];
    }
    const int64_t qk = (rank << 43) | ctr;
    int64_t lo = 0, hi = S;
    while (lo < hi) {
      const int64_t mid = (lo + hi) >> 1;
      if (spans[mid].key <= qk)
        lo = mid + 1;
      else
        hi = mid;
    }
    if (lo == 0) return -1;
    const Span& sp = spans[lo - 1];
    if ((sp.key >> 43) != rank) return -1;
    if (ctr < sp.start || ctr >= sp.start + sp.n) return -1;
    memo_span = lo - 1;
    return newrow[sp.row_off + (ctr - sp.start)];
  };

  // ---- 3. constant-column fills + one fused gather pass -------------------
  const bool c_obj = g_flags[1] != 0;
  const int64_t c_sid = g_flags[4];  // 0 none, 1 all-seq, 2 const map prop
  const bool c_ins = g_flags[7] != 0, c_exp = g_flags[8] != 0;
  const bool c_vc = g_flags[9] != 0, c_vl = g_flags[10] != 0;
  const bool c_vo = g_flags[11] != 0, c_vi = g_flags[12] != 0;
  const bool c_w = g_flags[13] != 0, c_mark = g_flags[14] != 0;
  if (c_obj) std::fill(obj_key, obj_key + N, g_vals[1]);
  // prop defaults to -1 via ONE memset; the gather loop then only writes
  // map-prop rows (real logs are sequence-dominated)
  if (c_sid != 2) std::memset(prop, 0xFF, (size_t)N * sizeof(int32_t));
  if (c_sid == 2) {
    std::fill(prop, prop + N, (int32_t)g_vals[4]);
    std::fill(elem_ref, elem_ref + N, ELEM_MAP);
  }
  if (c_ins) std::fill(insert, insert + N, (uint8_t)g_vals[7]);
  if (c_exp) std::fill(expand, expand + N, (uint8_t)g_vals[8]);
  if (c_vc) {
    std::fill(vcode, vcode + N, (int32_t)g_vals[9]);
    const int32_t vt =
        g_vals[9] > TAG_UNKNOWN ? TAG_UNKNOWN : (int32_t)g_vals[9];
    std::fill(value_tag, value_tag + N, vt);
  }
  if (c_vl) std::fill(vlen, vlen + N, g_vals[10]);
  if (c_vo) std::fill(voff, voff + N, g_vals[11]);
  if (c_vi) std::fill(value_int, value_int + N, g_vals[12]);
  if (c_w) std::fill(width, width + N, (int32_t)g_vals[13]);
  if (c_mark) std::fill(mark_idx, mark_idx + N, (int32_t)g_vals[14]);

  // (obj_table fills alongside; make ranks resolve later by binary search
  // over it — the table is tiny, and this drops the old N-row make_prefix
  // stream entirely)
  obj_table[0] = 0;
  int64_t n_make = 0;
  for (int64_t j = 0; j < N; j++) {
    const int64_t c = src_c[j];
    const int64_t i = src[j] - row_off[c];
    const int64_t* ptrs = col_ptrs + c * 19;
    const uint8_t* rec = (const uint8_t*)(uintptr_t)ptrs[18] + i * 24;
    id_key[j] = ((start_op[c] + i) << AB) | author_rank[c];
    const int32_t a = rec[20];
    action[j] = a;
    if (is_make_action(a)) obj_table[1 + n_make++] = id_key[j];
    if (!c_ins) insert[j] = rec[22];
    if (!c_exp) expand[j] = ((const uint8_t*)(uintptr_t)ptrs[8])[i];
    if (!c_vc) {
      const int32_t vc = rec[21];
      vcode[j] = vc;
      value_tag[j] = vc > TAG_UNKNOWN ? TAG_UNKNOWN : vc;
    }
    if (!c_vl) vlen[j] = *(const uint32_t*)(rec + 12);
    if (!c_vo) voff[j] = (int64_t)*(const uint32_t*)(rec + 8) + raw_base[c];
    if (!c_vi) value_int[j] = ((const int64_t*)(uintptr_t)ptrs[12])[i];
    if (!c_w) width[j] = ((const int32_t*)(uintptr_t)ptrs[13])[i];
    // object id (per-change const shortcut first: nearly every real
    // change targets one object, so the has/actor/ctr loads + table
    // translation collapse to a single C-array read)
    if (!c_obj) {
      const int64_t cobj = c_obj_key[c];
      if (cobj >= 0) {
        obj_key[j] = cobj;
      } else if (((const uint8_t*)(uintptr_t)ptrs[3])[i]) {
        const int32_t oa = ((const int32_t*)(uintptr_t)ptrs[2])[i];
        if (oa < 0 || oa >= tab_size[c]) return -4;
        const int64_t octr = ((const int64_t*)(uintptr_t)ptrs[1])[i];
        if (octr < 0 || octr >= ((int64_t)1 << 43)) return -5;
        obj_key[j] = (octr << AB) | tab_all[tab_off[c] + oa];
      } else {
        obj_key[j] = 0;
      }
    }
    // key: map prop or sequence element
    if (c_sid != 2) {
      const int64_t csid = c_sid == 1 ? -1 : c_sid_arr[c];
      const int32_t sid =
          csid != -2 ? -1 : ((const int32_t*)(uintptr_t)ptrs[4])[i];
      if (csid >= 0) {
        prop[j] = (int32_t)csid;
        elem_ref[j] = ELEM_MAP;
      } else if (sid >= 0) {
        if (prop_off[c] < 0 || sid >= prop_size[c]) return -6;
        prop[j] = prop_remap_all[prop_off[c] + sid];
        elem_ref[j] = ELEM_MAP;
      } else {
        const int64_t ectr = *(const int64_t*)(rec + 0);
        if (ectr == 0) {
          elem_ref[j] = ELEM_HEAD;
        } else {
          const int32_t ea = *(const int32_t*)(rec + 16);
          if (ea < 0 || ea >= tab_size[c]) return -7;
          if (ectr < 0 || ectr >= ((int64_t)1 << 43)) return -8;
          const int32_t r = resolve2(ectr, tab_all[tab_off[c] + ea], c);
          elem_ref[j] = r < 0 ? ELEM_MISSING : r;
        }
      }
    }
    // mark name
    if (!c_mark) {
      const int32_t ms = ((const int32_t*)(uintptr_t)ptrs[14])[i];
      if (ms >= 0) {
        if (mark_off[c] < 0 || ms >= mark_size[c]) return -9;
        mark_idx[j] = mark_remap_all[mark_off[c] + ms];
      } else {
        mark_idx[j] = -1;
      }
    }
  }
  out_meta[0] = 1 + n_make;
  tick("gather");

  // ---- 4. dense object ids ------------------------------------------------
  // ops overwhelmingly share their container: a one-entry memo turns the
  // resolve into a single compare for nearly every row. The make RANK of a
  // resolved row comes from a binary search over the (tiny, L1-resident)
  // obj_table instead of the old N-row make_prefix stream.
  auto make_rank = [&](int32_t r) -> int64_t {
    const int64_t idk = id_key[r];
    int64_t lo = 0, hi = n_make;
    while (lo < hi) {
      const int64_t mid = (lo + hi) >> 1;
      if (obj_table[1 + mid] < idk)
        lo = mid + 1;
      else
        hi = mid;
    }
    if (lo >= n_make || obj_table[1 + lo] != idk) return -1;
    return lo;
  };
  bool obj_fallback = false;
  if (c_obj) {
    const int64_t k = g_vals[1];
    int32_t dense = 0;
    if (k != 0) {
      const int32_t r =
          resolve2(k >> AB, k & (((int64_t)1 << AB) - 1), src_c[0]);
      const int64_t mr = r < 0 || !is_make_action(action[r])
                             ? -1
                             : make_rank(r);
      if (mr < 0)
        obj_fallback = true;
      else
        dense = (int32_t)(1 + mr);
    }
    if (!obj_fallback) std::fill(obj_dense, obj_dense + N, dense);
  } else {
    int64_t memo_obj_key = -1;
    int32_t memo_obj_dense = 0;
    for (int64_t j = 0; j < N; j++) {
      const int64_t k = obj_key[j];
      if (k == 0) {
        obj_dense[j] = 0;
        continue;
      }
      if (k == memo_obj_key) {
        obj_dense[j] = memo_obj_dense;
        continue;
      }
      const int32_t r = resolve2(k >> AB, k & (((int64_t)1 << AB) - 1),
                                 src_c[j]);
      const int64_t mr = r < 0 || !is_make_action(action[r])
                             ? -1
                             : make_rank(r);
      if (mr < 0) {
        obj_fallback = true;  // partial history: host recomputes the table
        break;
      }
      memo_obj_key = k;
      memo_obj_dense = (int32_t)(1 + mr);
      obj_dense[j] = memo_obj_dense;
    }
  }

  tick("objdense");
  // ---- 5. pred edges -------------------------------------------------------
  for (int64_t c = 0; c < C; c++) {
    const int32_t* pnum = (const int32_t*)cp(c, 15);
    const int64_t* pctr = (const int64_t*)cp(c, 16);
    const int32_t* pact = (const int32_t*)cp(c, 17);
    int64_t k = pred_off[c];
    const int64_t kend = pred_off[c + 1];
    for (int64_t i = 0; i < n_ops[c]; i++) {
      const int32_t np = pnum[i];
      if (np < 0 || k + np > kend) return -10;
      for (int32_t e = 0; e < np; e++, k++) {
        const int64_t pc_local = k - pred_off[c];
        const int64_t ctr = pctr[pc_local];
        const int32_t pa = pact[pc_local];
        if (pa < 0 || pa >= tab_size[c]) return -11;
        if (ctr < 0 || ctr >= ((int64_t)1 << 43)) return -12;
        pred_src[k] = newrow[row_off[c] + i];
        pred_tgt[k] = resolve2(ctr, tab_all[tab_off[c] + pa], c);
      }
    }
    if (k != kend) return -13;  // pred_num sum != q_ops for this change
  }

  tick("pred");
  return obj_fallback ? 1 : 0;
}

}  // extern "C"
