// Batch column extraction: decode MANY changes' op columns in one native
// call per column kind, writing straight into unified output arrays.
//
// This is the load half of the north-star pipeline (BASELINE.json): the
// change chunk's columnar encoding (reference:
// rust/automerge/src/storage/change/change_op_columns.rs) goes to numpy
// arrays without a per-change Python/FFI round trip — the per-change
// overhead of the one-change-at-a-time path dominated extraction time.
//
// Layout contract (shared by all batch entry points):
//   buf       — all changes' bytes for this column, concatenated
//   off/len   — per-change slice of buf (len 0 = column absent)
//   row_off   — per-change output row offset; row_off[n_changes] = total
// Per change, exactly row_off[c+1]-row_off[c] rows are produced: a short
// column is padded with nulls, a long one is an error. Error return is
// -(c+1) for the first malformed change.

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

using i64 = long long;
using i32 = int32_t;
using u8 = uint8_t;

// Decoders mirrored from codecs.cpp (kept static-local to this TU).
inline int dec_uleb(const u8* p, size_t n, uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  for (size_t i = 0; i < n && i < 10; i++) {
    uint64_t b = p[i] & 0x7f;
    if (shift == 63 && b > 1) return -1;
    v |= b << shift;
    if (!(p[i] & 0x80)) {
      if (i > 0 && p[i] == 0) return -1;
      *out = v;
      return (int)(i + 1);
    }
    shift += 7;
  }
  return -1;
}

inline int dec_sleb(const u8* p, size_t n, int64_t* out) {
  int64_t v = 0;
  int shift = 0;
  for (size_t i = 0; i < n && i < 10; i++) {
    u8 byte = p[i];
    if (shift == 63 && (byte & 0x7f) != 0 && (byte & 0x7f) != 0x7f) return -1;
    v |= (int64_t)(byte & 0x7f) << shift;
    shift += 7;
    if (!(byte & 0x80)) {
      if (shift < 64 && (byte & 0x40)) v |= -((int64_t)1 << shift);
      if (i > 0) {
        u8 prev = p[i - 1];
        if (byte == 0 && !(prev & 0x40) && (prev & 0x80)) return -1;
        if (byte == 0x7f && (prev & 0x40) && (prev & 0x80)) return -1;
      }
      *out = v;
      return (int)(i + 1);
    }
  }
  return -1;
}

// One change's RLE column into out[0..cap); returns rows decoded or -1;
// sets *overrun if the input holds more rows than cap.
i64 rle_one(const u8* buf, size_t len, int signed_vals, i64* out, u8* mask,
            size_t cap, bool* overrun) {
  size_t pos = 0, row = 0;
  *overrun = false;
  while (pos < len) {
    int64_t header;
    int c = dec_sleb(buf + pos, len - pos, &header);
    if (c < 0) return -1;
    pos += (size_t)c;
    if (header > 0) {
      int64_t value;
      if (signed_vals) {
        c = dec_sleb(buf + pos, len - pos, &value);
      } else {
        uint64_t uv;
        c = dec_uleb(buf + pos, len - pos, &uv);
        value = (int64_t)uv;
      }
      if (c < 0) return -1;
      pos += (size_t)c;
      for (int64_t i = 0; i < header; i++) {
        if (row >= cap) { *overrun = true; return (i64)row; }
        out[row] = value;
        mask[row] = 1;
        row++;
      }
    } else if (header < 0) {
      for (int64_t i = 0; i < -header; i++) {
        int64_t value;
        if (signed_vals) {
          c = dec_sleb(buf + pos, len - pos, &value);
        } else {
          uint64_t uv;
          c = dec_uleb(buf + pos, len - pos, &uv);
          value = (int64_t)uv;
        }
        if (c < 0) return -1;
        pos += (size_t)c;
        if (row >= cap) { *overrun = true; return (i64)row; }
        out[row] = value;
        mask[row] = 1;
        row++;
      }
    } else {
      uint64_t nulls;
      c = dec_uleb(buf + pos, len - pos, &nulls);
      if (c < 0) return -1;
      pos += (size_t)c;
      for (uint64_t i = 0; i < nulls; i++) {
        if (row >= cap) { *overrun = true; return (i64)row; }
        out[row] = 0;
        mask[row] = 0;
        row++;
      }
    }
  }
  return (i64)row;
}

}  // namespace

extern "C" {

i64 am_rle_decode_batch(const u8* buf, const i64* off, const i64* len,
                        const i64* row_off, i64 n_changes, int signed_vals,
                        i64* out, u8* mask) {
  for (i64 c = 0; c < n_changes; c++) {
    i64 lo = row_off[c], hi = row_off[c + 1];
    bool overrun;
    i64 n = rle_one(buf + off[c], (size_t)len[c], signed_vals, out + lo,
                    mask + lo, (size_t)(hi - lo), &overrun);
    if (n < 0 || overrun) return -(c + 1);
    for (i64 r = lo + n; r < hi; r++) {  // pad short columns with nulls
      out[r] = 0;
      mask[r] = 0;
    }
  }
  return 0;
}

// Delta: RLE of differences with the running absolute reset per change.
i64 am_delta_decode_batch(const u8* buf, const i64* off, const i64* len,
                          const i64* row_off, i64 n_changes, i64* out,
                          u8* mask) {
  i64 rc = am_rle_decode_batch(buf, off, len, row_off, n_changes, 1, out, mask);
  if (rc != 0) return rc;
  for (i64 c = 0; c < n_changes; c++) {
    int64_t absolute = 0;
    for (i64 r = row_off[c]; r < row_off[c + 1]; r++) {
      if (mask[r]) {
        absolute += out[r];
        out[r] = absolute;
      }
    }
  }
  return 0;
}

i64 am_bool_decode_batch(const u8* buf, const i64* off, const i64* len,
                         const i64* row_off, i64 n_changes, u8* out) {
  for (i64 c = 0; c < n_changes; c++) {
    i64 lo = row_off[c], hi = row_off[c + 1];
    size_t pos = 0, row = 0, cap = (size_t)(hi - lo);
    const u8* p = buf + off[c];
    size_t n = (size_t)len[c];
    u8 value = 1;
    while (pos < n) {
      uint64_t run;
      int k = dec_uleb(p + pos, n - pos, &run);
      if (k < 0) return -(c + 1);
      pos += (size_t)k;
      value = !value;
      if (run > cap - row) return -(c + 1);  // longer than op count
      memset(out + lo + row, value, (size_t)run);
      row += (size_t)run;
    }
    memset(out + lo + row, 0, cap - row);
  }
  return 0;
}

// String-RLE columns (map keys, mark names) decoded + content-interned in
// one pass. Per row: the interned string id (or -1 for null). The table is
// returned as (tab_off, tab_len) slices of `buf` in first-seen order.
// Returns the table size, or -(c+1) on error, or -1000000000 - needed if
// the table overflows max_tab.
i64 am_rle_decode_batch_strtab(const u8* buf, const i64* off, const i64* len,
                               const i64* row_off, i64 n_changes,
                               i32* out_ids, i64* tab_off, i64* tab_len,
                               i64 max_tab) {
  std::unordered_map<std::string, i32> intern;
  i64 tab_n = 0;
  for (i64 c = 0; c < n_changes; c++) {
    i64 lo = row_off[c], hi = row_off[c + 1];
    size_t cap = (size_t)(hi - lo), row = 0, pos = 0;
    const u8* p = buf + off[c];
    size_t n = (size_t)len[c];
    while (pos < n) {
      int64_t header;
      int k = dec_sleb(p + pos, n - pos, &header);
      if (k < 0) return -(c + 1);
      pos += (size_t)k;
      if (header == 0) {
        uint64_t nulls;
        k = dec_uleb(p + pos, n - pos, &nulls);
        if (k < 0) return -(c + 1);
        pos += (size_t)k;
        if (nulls > cap - row) return -(c + 1);
        for (uint64_t i = 0; i < nulls; i++) out_ids[lo + row++] = -1;
        continue;
      }
      i64 count = header > 0 ? header : -header;
      for (i64 rep = 0; rep < (header > 0 ? 1 : count); rep++) {
        uint64_t slen;
        k = dec_uleb(p + pos, n - pos, &slen);
        if (k < 0) return -(c + 1);
        pos += (size_t)k;
        if (slen > n - pos) return -(c + 1);
        std::string s((const char*)(p + pos), (size_t)slen);
        auto it = intern.find(s);
        i32 id;
        if (it == intern.end()) {
          if (tab_n >= max_tab) return -1000000000 - (tab_n + 1);
          id = (i32)tab_n;
          tab_off[tab_n] = (i64)(off[c] + (i64)pos);
          tab_len[tab_n] = (i64)slen;
          tab_n++;
          intern.emplace(std::move(s), id);
        } else {
          id = it->second;
        }
        pos += (size_t)slen;
        i64 reps = header > 0 ? count : 1;
        if ((i64)row + reps > (i64)cap) return -(c + 1);
        for (i64 i = 0; i < reps; i++) out_ids[lo + row++] = id;
      }
    }
    for (; row < cap; row++) out_ids[lo + row] = -1;
  }
  return tab_n;
}

// Integer value payloads: decode LEB at (voff, vlen) for rows whose code is
// an integer kind (3 = uleb uint; 4/8/9 = sleb int/counter/timestamp).
i64 am_leb_decode_rows(const u8* raw, i64 raw_len, const i64* voff,
                       const i64* vlen, const i32* vcode, i64 n, i64* out) {
  for (i64 r = 0; r < n; r++) {
    i32 code = vcode[r];
    out[r] = code == 2 ? 1 : 0;  // boolean true is payload-free
    if (vlen[r] <= 0) continue;
    if (code != 3 && code != 4 && code != 8 && code != 9) continue;
    if (voff[r] < 0 || voff[r] + vlen[r] > raw_len) return -(r + 1);
    const u8* p = raw + voff[r];
    if (code == 3) {
      uint64_t v;
      if (dec_uleb(p, (size_t)vlen[r], &v) < 0) return -(r + 1);
      out[r] = (i64)v;
    } else {
      int64_t v;
      if (dec_sleb(p, (size_t)vlen[r], &v) < 0) return -(r + 1);
      out[r] = v;
    }
  }
  return 0;
}

}  // extern "C"
