// Chain condensation for document-order ranking.
//
// Preorder over the sibling forest is a linked list: succ(v) = first
// child if any, else the next sibling of the nearest ancestor that has
// one (the reference resolves the same order one op at a time through
// query/insert.rs). Maximal FIRST-CHILD chains are contiguous runs of
// that list, and — because a non-first child is always a chain head —
// the condensed successor graph is chain-to-chain. Collapsing chains
// shrinks the iterative ranking problem from N elements to R chains
// (typing runs make R << N), which is what lets the multi-chip path
// move O(R)-sized collectives per doubling step instead of O(N)
// (parallel/sharding.py) and the all-device kernel gather R-sized
// arrays (ops/merge.py).
//
// This pass is one sequential O(N) walk on the host; the iterative
// (log-depth) ranking it feeds stays on the device mesh.

#include <cstddef>
#include <cstdint>
#include <vector>

extern "C" {

// Node space follows ops/merge.py forest(): rows [0,P) are ops, [P,
// P+n_objs) object roots, last slot the sentinel. parent_row is
// node-space (root parents >= P); is_elem marks insert rows.
//
// Outputs (caller-allocated): per element chain_id/offset (-1/0 for
// non-elements); per chain (capacity P): head row, length, tail_ans
// (next sibling of the deepest sibling-bearing member — the climb's
// within-chain answer), cpar (chain of the head's parent, -1 when the
// parent is an object root = the climb terminates), centry (the
// within-chain climb answer at the head's parent's offset);
// start_chain[o] = chain of object o's first child (-1 when empty).
// Returns R (chain count), or -1 on malformed structure.
long long am_chain_condense(const int32_t* first_child,
                            const int32_t* next_sib,
                            const int32_t* parent_row,
                            const uint8_t* is_elem, int64_t P,
                            int64_t n_objs, int32_t* chain_id,
                            int32_t* offset, int32_t* chain_head,
                            int32_t* chain_len, int32_t* chain_tail_ans,
                            int32_t* chain_cpar, int32_t* chain_centry,
                            int32_t* start_chain) {
  std::vector<int32_t> prefix_ans((size_t)P, -1);
  for (int64_t v = 0; v < P; v++) {
    chain_id[v] = -1;
    offset[v] = 0;
  }
  int64_t R = 0;
  for (int64_t v = 0; v < P; v++) {
    if (!is_elem[v]) continue;
    const int32_t p = parent_row[v];
    // head: parent is an object root, or v is not its parent's first
    // child (non-first children always start a chain)
    const bool head = p >= P || first_child[p] != v;
    if (!head) continue;
    const int64_t c = R++;
    chain_head[c] = (int32_t)v;
    int32_t carry = -1;
    int64_t u = v, o = 0;
    for (;;) {
      if (chain_id[u] != -1) return -1;  // fc cycle: malformed forest
      chain_id[u] = (int32_t)c;
      offset[u] = (int32_t)o;
      if (next_sib[u] >= 0) carry = next_sib[u];
      prefix_ans[u] = carry;
      const int32_t fc = first_child[u];
      if (fc < 0 || fc >= P) break;  // tail (roots never appear as fc)
      u = fc;
      o++;
    }
    chain_len[c] = (int32_t)(o + 1);
    chain_tail_ans[c] = carry;
  }
  // every element must have been claimed by exactly one walk
  for (int64_t v = 0; v < P; v++)
    if (is_elem[v] && chain_id[v] < 0) return -1;
  // second pass: parent links (the parent's chain may have any id)
  for (int64_t c = 0; c < R; c++) {
    const int32_t p = parent_row[chain_head[c]];
    if (p >= P) {
      chain_cpar[c] = -1;
      chain_centry[c] = -1;
    } else {
      chain_cpar[c] = chain_id[p];
      chain_centry[c] = prefix_ans[p];
    }
  }
  for (int64_t o = 0; o < n_objs; o++) {
    const int32_t fc = first_child[P + o];
    start_chain[o] = (fc >= 0 && fc < P) ? chain_id[fc] : -1;
  }
  return R;
}

}  // extern "C"
