// Native columnar codec core: LEB128, RLE, delta-RLE, boolean run-length.
//
// The byte-hot loops of the storage layer (the reference implements these in
// Rust: rust/automerge/src/columnar/encoding/{rle.rs,delta.rs,boolean.rs}).
// Byte-compatible with automerge_tpu/utils/codecs.py — change hashes are
// computed over these bytes, so the encoder state machine is mirrored
// exactly (verified by differential tests in tests/test_native_codecs.py).
//
// C ABI over raw buffers; loaded via ctypes (automerge_tpu/native/__init__).
// All decoders are bounds-checked and clamp attacker-controlled run lengths
// to the caller's capacity.

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int64_t I64_MAX = INT64_MAX;
constexpr int64_t I64_MIN = INT64_MIN;

inline int64_t sat_add(int64_t a, int64_t b) {
    int64_t r;
    if (__builtin_add_overflow(a, b, &r)) return b > 0 ? I64_MAX : I64_MIN;
    return r;
}

inline int64_t sat_sub(int64_t a, int64_t b) {
    int64_t r;
    if (__builtin_sub_overflow(a, b, &r)) return b < 0 ? I64_MAX : I64_MIN;
    return r;
}

// -- LEB128 -----------------------------------------------------------------

// Decode ULEB128; returns bytes consumed or -1 on error/truncation.
inline int dec_uleb(const uint8_t* p, size_t n, uint64_t* out) {
    uint64_t v = 0;
    int shift = 0;
    for (size_t i = 0; i < n && i < 10; i++) {
        uint64_t b = p[i] & 0x7f;
        if (shift == 63 && b > 1) return -1;  // overflow u64
        v |= b << shift;
        if (!(p[i] & 0x80)) {
            // reject non-canonical (overlong) encodings like the reference
            if (i > 0 && p[i] == 0) return -1;
            *out = v;
            return (int)(i + 1);
        }
        shift += 7;
    }
    return -1;
}

inline int dec_sleb(const uint8_t* p, size_t n, int64_t* out) {
    int64_t v = 0;
    int shift = 0;
    for (size_t i = 0; i < n && i < 10; i++) {
        uint8_t byte = p[i];
        if (shift == 63 && (byte & 0x7f) != 0 && (byte & 0x7f) != 0x7f)
            return -1;
        v |= (int64_t)(byte & 0x7f) << shift;
        shift += 7;
        if (!(byte & 0x80)) {
            if (shift < 64 && (byte & 0x40)) v |= -((int64_t)1 << shift);
            // reject overlong: a final 0x00 after continuation with no sign
            // effect, or 0x7f extending a negative number redundantly
            if (i > 0) {
                uint8_t prev = p[i - 1];
                if (byte == 0 && !(prev & 0x40) && (prev & 0x80)) return -1;
                if (byte == 0x7f && (prev & 0x40) && (prev & 0x80)) return -1;
            }
            *out = v;
            return (int)(i + 1);
        }
    }
    return -1;
}

inline void enc_uleb(uint64_t v, uint8_t* out, size_t* w) {
    do {
        uint8_t b = v & 0x7f;
        v >>= 7;
        if (v) b |= 0x80;
        out[(*w)++] = b;
    } while (v);
}

inline void enc_sleb(int64_t v, uint8_t* out, size_t* w) {
    bool more = true;
    while (more) {
        uint8_t b = v & 0x7f;
        v >>= 7;  // arithmetic shift
        if ((v == 0 && !(b & 0x40)) || (v == -1 && (b & 0x40))) more = false;
        else b |= 0x80;
        out[(*w)++] = b;
    }
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// RLE decode: values into out[], validity into mask[] (1 = present).
// signed_vals: 0 = ULEB values, 1 = SLEB values.
// Returns number of rows decoded, or -1 on malformed input.
long long am_rle_decode_i64(const uint8_t* buf, size_t len, int signed_vals,
                            int64_t* out, uint8_t* mask, size_t capacity) {
    size_t pos = 0, row = 0;
    while (pos < len && row < capacity) {
        int64_t header;
        int c = dec_sleb(buf + pos, len - pos, &header);
        if (c < 0) return -1;
        pos += (size_t)c;
        if (header > 0) {
            int64_t value;
            if (signed_vals) {
                c = dec_sleb(buf + pos, len - pos, &value);
            } else {
                uint64_t uv;
                c = dec_uleb(buf + pos, len - pos, &uv);
                value = (int64_t)uv;
            }
            if (c < 0) return -1;
            pos += (size_t)c;
            size_t take = (size_t)header;
            if (take > capacity - row) take = capacity - row;
            for (size_t i = 0; i < take; i++) {
                out[row] = value;
                mask[row] = 1;
                row++;
            }
        } else if (header < 0) {
            size_t litn = (size_t)(-header);
            for (size_t i = 0; i < litn; i++) {
                int64_t value;
                if (signed_vals) {
                    c = dec_sleb(buf + pos, len - pos, &value);
                } else {
                    uint64_t uv;
                    c = dec_uleb(buf + pos, len - pos, &uv);
                    value = (int64_t)uv;
                }
                if (c < 0) return -1;
                pos += (size_t)c;
                if (row < capacity) {
                    out[row] = value;
                    mask[row] = 1;
                    row++;
                }
            }
        } else {
            uint64_t nulls;
            c = dec_uleb(buf + pos, len - pos, &nulls);
            if (c < 0) return -1;
            pos += (size_t)c;
            size_t take = (size_t)nulls;
            if (take > capacity - row) take = capacity - row;
            for (size_t i = 0; i < take; i++) {
                out[row] = 0;
                mask[row] = 0;
                row++;
            }
        }
    }
    return (long long)row;
}

// Delta decode: RLE of successive differences, absolute from 0 (saturating).
long long am_delta_decode_i64(const uint8_t* buf, size_t len, int64_t* out,
                              uint8_t* mask, size_t capacity) {
    long long n = am_rle_decode_i64(buf, len, 1, out, mask, capacity);
    if (n < 0) return n;
    int64_t absolute = 0;
    for (long long i = 0; i < n; i++) {
        if (mask[i]) {
            absolute = sat_add(absolute, out[i]);
            out[i] = absolute;
        }
    }
    return n;
}

// Boolean decode: alternating ULEB run lengths starting with false.
long long am_bool_decode(const uint8_t* buf, size_t len, uint8_t* out,
                         size_t capacity) {
    size_t pos = 0, row = 0;
    uint8_t value = 1;
    while (pos < len && row < capacity) {
        uint64_t run;
        int c = dec_uleb(buf + pos, len - pos, &run);
        if (c < 0) return -1;
        pos += (size_t)c;
        value = !value;
        size_t take = (size_t)run;
        if (take > capacity - row) take = capacity - row;
        memset(out + row, value, take);
        row += take;
    }
    return (long long)row;
}

// ---------------------------------------------------------------------------
// RLE encode: mirrors the Python state machine byte-for-byte
// (utils/codecs.py RleEncoder). out must hold >= 12*n + 16 bytes.
// Returns bytes written, or -1 if out_cap is too small.

namespace {

struct Writer {
    uint8_t* out;
    size_t cap;
    size_t w = 0;
    bool ok = true;
    void need(size_t k) {
        if (w + k > cap) ok = false;
    }
    void sleb(int64_t v) {
        need(10);
        if (ok) enc_sleb(v, out, &w);
    }
    void uleb(uint64_t v) {
        need(10);
        if (ok) enc_uleb(v, out, &w);
    }
    void value(int64_t v, int signed_vals) {
        need(10);
        if (!ok) return;
        if (signed_vals) enc_sleb(v, out, &w);
        else enc_uleb((uint64_t)v, out, &w);
    }
};

}  // namespace

long long am_rle_encode_i64(const int64_t* vals, const uint8_t* mask, size_t n,
                            int signed_vals, uint8_t* out, size_t out_cap) {
    Writer wr{out, out_cap};
    size_t i = 0;
    while (i < n && wr.ok) {
        if (!mask[i]) {  // null run
            size_t j = i;
            while (j < n && !mask[j]) j++;
            // an all-null column encodes to zero bytes; trailing nulls after
            // values DO flush (mirrors Python: only finish() in NULLS state
            // flushes, INITIAL_NULLS at finish emits nothing)
            if (i == 0 && j == n) return 0;
            wr.sleb(0);
            wr.uleb((uint64_t)(j - i));
            i = j;
            continue;
        }
        // count the run of equal values
        size_t j = i + 1;
        while (j < n && mask[j] && vals[j] == vals[i]) j++;
        size_t run = j - i;
        if (run >= 2) {
            wr.sleb((int64_t)run);
            wr.value(vals[i], signed_vals);
            i = j;
            continue;
        }
        // literal run: values until a pair of equal values or a null
        size_t lit_start = i;
        while (true) {
            if (j >= n || !mask[j]) break;      // next is null/end: lone tail
            if (vals[j] == vals[j - 1]) {       // a run starts at j-1
                j--;
                break;
            }
            j++;
        }
        size_t litn = j - lit_start;
        wr.sleb(-(int64_t)litn);
        for (size_t k = lit_start; k < j && wr.ok; k++)
            wr.value(vals[k], signed_vals);
        i = j;
    }
    return wr.ok ? (long long)wr.w : -1;
}

long long am_delta_encode_i64(const int64_t* vals, const uint8_t* mask,
                              size_t n, uint8_t* out, size_t out_cap,
                              int64_t* scratch) {
    int64_t absolute = 0;
    for (size_t i = 0; i < n; i++) {
        if (mask[i]) {
            scratch[i] = sat_sub(vals[i], absolute);
            absolute = vals[i];
        } else {
            scratch[i] = 0;
        }
    }
    return am_rle_encode_i64(scratch, mask, n, 1, out, out_cap);
}

long long am_bool_encode(const uint8_t* vals, size_t n, uint8_t* out,
                         size_t out_cap) {
    Writer wr{out, out_cap};
    uint8_t last = 0;
    size_t count = 0;
    for (size_t i = 0; i < n && wr.ok; i++) {
        uint8_t v = vals[i] ? 1 : 0;
        if (v == last) {
            count++;
        } else {
            wr.uleb((uint64_t)count);
            last = v;
            count = 1;
        }
    }
    if (count > 0 && wr.ok) wr.uleb((uint64_t)count);
    return wr.ok ? (long long)wr.w : -1;
}

}  // extern "C"

extern "C" {

// ---------------------------------------------------------------------------
// Preorder document-order ranking for the RGA insert forest.
//
// Node space mirrors ops/merge.py: element nodes [0, P), object roots
// [P, N-1), sentinel N-1. first_child / next_sib / parent are int32 node
// ids with -1 = none. Writes the preorder index of every element node
// (per its object's traversal) into out[0..P) (-1 for non-elements).
// Sequential pointer chase: O(n), cache-friendly — the host half of the
// hybrid merge pipeline. Returns 0, or -1 if the structure is cyclic.
long long am_preorder_index(const int32_t* first_child, const int32_t* next_sib,
                            const int32_t* parent, int64_t P, int64_t N,
                            int32_t* out) {
    // Explicit-stack preorder: push the pending sibling when descending, so
    // chain tails never climb the parent chain (the old climb was O(depth)
    // per tail — on chain-heavy logs that re-walked whole insert runs).
    // RGA trees are chains of CONSECUTIVE rows almost everywhere (an insert
    // run's child is the next row), so the hot path reads first_child /
    // next_sib sequentially and the stack stays near-empty.
    (void)parent;
    for (int64_t i = 0; i < P; i++) out[i] = -1;
    std::vector<int32_t> stack;
    stack.reserve(64);
    int64_t budget = 2 * N + 8;  // cycle guard
    for (int64_t r = P; r < N - 1; r++) {
        int32_t cur = first_child[r];
        int32_t idx = 0;
        while (cur >= 0 && cur < P) {
            if (--budget < 0) return -1;
            if (out[cur] >= 0) return -1;  // shared node: cycle/overlap
            out[cur] = idx++;
            const int32_t ns = next_sib[cur];
            const int32_t fc = first_child[cur];
            if (fc >= 0) {
                if (ns >= 0) stack.push_back(ns);
                cur = fc;
            } else if (ns >= 0) {
                cur = ns;
            } else if (!stack.empty()) {
                cur = stack.back();
                stack.pop_back();
            } else {
                cur = -1;
            }
        }
        if (!stack.empty()) return -1;  // dangling pending siblings: corrupt
    }
    return 0;
}

}  // extern "C"
