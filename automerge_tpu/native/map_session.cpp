// Native map-put session: the local map-transaction hot path.
//
// The reference's local map op path (transaction/inner.rs:399-451
// local_map_op: pred lookup in the op tree, op insert, succ marking) runs
// per-op in Rust; the Python transaction layer pays ~13us/op on the same
// work. This session owns ONE map object's visible-winner state for the
// duration of a transaction: a put resolves its pred (the key's current
// winner) in a C++ hash map, encodes the scalar payload into the change
// column's raw form, and the emitted ops are exported as arrays for the
// array-native change encoder at commit (storage/change.py
// encode_ops_with_map_tail).
//
// Eligibility is gated by the Python wrapper (core/transaction.py
// fast_put_fn): MAP object, no conflicted (multi-winner) keys, no
// isolation scope, actor indices < 2^20. Ids pack as
// (counter << 20 | doc actor index), matching session.cpp.

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

using std::size_t;

namespace {

using i64 = long long;
using i32 = int32_t;
using u8 = uint8_t;

// value_meta type codes (types.py VALUE_TYPE_*, reference value.rs)
constexpr i32 V_NULL = 0;
constexpr i32 V_FALSE = 1;
constexpr i32 V_TRUE = 2;
constexpr i32 V_UINT = 3;  // uleb
constexpr i32 V_INT = 4;   // sleb
constexpr i32 V_F64 = 5;
constexpr i32 V_STR = 6;
constexpr i32 V_BYTES = 7;
constexpr i32 V_COUNTER = 8;    // sleb
constexpr i32 V_TIMESTAMP = 9;  // sleb

struct MOp {
  i64 id;       // packed (ctr << 20 | rank)
  i64 pred;     // overwritten winner id, 0 = fresh key
  i64 vmeta;    // (raw_len << 4) | type_code
  i64 raw_off;  // into MapSession::raw
  i64 raw_len;
  i32 key;      // interned key index
};

struct MapSession {
  std::unordered_map<std::string, i32> index;  // key -> key table index
  std::vector<i64> key_off;                    // n_keys+1 arena offsets
  std::vector<char> key_arena;                 // concatenated utf-8 keys
  std::vector<i64> winner;  // per key index: current winner id (0 = none)
  std::vector<MOp> ops;
  std::vector<u8> raw;  // concatenated value payload bytes
  i64 rank = 0;

  MapSession() { key_off.push_back(0); }

  i32 intern(const char* key, i64 len) {
    std::string k(key, (size_t)len);
    auto it = index.find(k);
    if (it != index.end()) return it->second;
    i32 idx = (i32)winner.size();
    index.emplace(std::move(k), idx);
    key_arena.insert(key_arena.end(), key, key + len);
    key_off.push_back((i64)key_arena.size());
    winner.push_back(0);
    return idx;
  }
};

void put_sleb(std::vector<u8>& out, i64 v) {
  for (;;) {
    u8 byte = (u8)(v & 0x7F);
    v >>= 7;  // arithmetic shift: sign-extends
    if ((v == 0 && !(byte & 0x40)) || (v == -1 && (byte & 0x40))) {
      out.push_back(byte);
      return;
    }
    out.push_back(byte | 0x80);
  }
}

void put_uleb(std::vector<u8>& out, unsigned long long v) {
  for (;;) {
    u8 byte = (u8)(v & 0x7F);
    v >>= 7;
    if (v == 0) {
      out.push_back(byte);
      return;
    }
    out.push_back(byte | 0x80);
  }
}

}  // namespace

extern "C" {

void* am_map_create(i64 rank) {
  auto* s = new MapSession();
  s->rank = rank;
  return s;
}

void am_map_destroy(void* p) { delete static_cast<MapSession*>(p); }

// Preload the object's visible keys: key i is key_bytes[key_offs[i] ..
// key_offs[i+1]) with current winner id winners[i]. Returns 0.
i64 am_map_init(void* p, const u8* key_bytes, const i64* key_offs,
                const i64* winners, i64 n) {
  MapSession& s = *static_cast<MapSession*>(p);
  for (i64 i = 0; i < n; i++) {
    i32 idx = s.intern((const char*)key_bytes + key_offs[i],
                       key_offs[i + 1] - key_offs[i]);
    s.winner[(size_t)idx] = winners[i];
  }
  return 0;
}

i64 am_map_op_count(void* p) {
  return (i64)static_cast<MapSession*>(p)->ops.size();
}

// One put. `code` is the value_meta type code; the payload is `ival` for
// int, `fval` for f64, `raw[0..raw_len)` for str/bytes, nothing for
// null/bool. Emits exactly one op (pred = the key's current winner) and
// promotes the new op to winner. Returns 1, or -3 for an unsupported code.
i64 am_map_put(void* p, i64 ctr, const char* key, i64 key_len, i32 code,
               i64 ival, double fval, const u8* rawv, i64 raw_len) {
  MapSession& s = *static_cast<MapSession*>(p);
  i32 kidx = s.intern(key, key_len);
  MOp op;
  op.id = (ctr << 20) | s.rank;
  op.key = kidx;
  op.pred = s.winner[(size_t)kidx];
  op.raw_off = (i64)s.raw.size();
  switch (code) {
    case V_NULL:
    case V_FALSE:
    case V_TRUE:
      break;
    case V_UINT:
      put_uleb(s.raw, (unsigned long long)ival);
      break;
    case V_INT:
    case V_COUNTER:
    case V_TIMESTAMP:
      put_sleb(s.raw, ival);
      break;
    case V_F64: {
      u8 buf[8];
      std::memcpy(buf, &fval, 8);  // x86/arm little-endian, like struct '<d'
      s.raw.insert(s.raw.end(), buf, buf + 8);
      break;
    }
    case V_STR:
    case V_BYTES:
      s.raw.insert(s.raw.end(), rawv, rawv + raw_len);
      break;
    default:
      return -3;
  }
  op.raw_len = (i64)s.raw.size() - op.raw_off;
  op.vmeta = (op.raw_len << 4) | code;
  s.ops.push_back(op);
  s.winner[(size_t)kidx] = op.id;
  return 1;
}

// Sizes needed to export ops [start, op_count): rows and raw-payload bytes.
i64 am_map_export_sizes(void* p, i64 start, i64* n_rows, i64* raw_bytes) {
  MapSession& s = *static_cast<MapSession*>(p);
  if (start < 0 || (size_t)start > s.ops.size()) return -1;
  *n_rows = (i64)s.ops.size() - start;
  i64 rb = 0;
  for (size_t i = (size_t)start; i < s.ops.size(); i++) rb += s.ops[i].raw_len;
  *raw_bytes = rb;
  return 0;
}

// Export emitted ops [start, op_count) in id (emission) order. Arrays must
// hold the counts from am_map_export_sizes. Returns rows written.
i64 am_map_export(void* p, i64 start, i64* ids, i64* key_idx, i64* preds,
                  i64* vmeta, u8* raw_out) {
  MapSession& s = *static_cast<MapSession*>(p);
  if (start < 0 || (size_t)start > s.ops.size()) return -1;
  i64 w = 0;
  i64 roff = 0;
  for (size_t i = (size_t)start; i < s.ops.size(); i++, w++) {
    const MOp& o = s.ops[i];
    ids[w] = o.id;
    key_idx[w] = o.key;
    preds[w] = o.pred;
    vmeta[w] = o.vmeta;
    std::memcpy(raw_out + roff, s.raw.data() + o.raw_off, (size_t)o.raw_len);
    roff += o.raw_len;
  }
  return w;
}

// Key-table export: sizes, then bytes + n_keys+1 offsets.
i64 am_map_keytab_sizes(void* p, i64* n_keys, i64* total_bytes) {
  MapSession& s = *static_cast<MapSession*>(p);
  *n_keys = (i64)s.winner.size();
  *total_bytes = (i64)s.key_arena.size();
  return 0;
}

i64 am_map_keytab(void* p, u8* bytes_out, i64* offs_out) {
  MapSession& s = *static_cast<MapSession*>(p);
  std::memcpy(bytes_out, s.key_arena.data(), s.key_arena.size());
  std::memcpy(offs_out, s.key_off.data(), s.key_off.size() * sizeof(i64));
  return (i64)s.winner.size();
}

}  // extern "C"
