"""Native codec loader: compiles codecs.cpp on first use, loads via ctypes.

The reference's storage layer is native (Rust); ours keeps the byte-hot
columnar codec loops in C++ with a pure-Python fallback (utils/codecs.py)
when no compiler is available. Set AUTOMERGE_TPU_NO_NATIVE=1 to force the
fallback.

Array-level API (numpy in/out):
    rle_decode_array(buf, signed_vals, capacity) -> (values i64, mask bool)
    delta_decode_array(buf, capacity) -> (values, mask)
    bool_decode_array(buf, capacity) -> bool array
    rle_encode_array(values, mask, signed_vals) -> bytes
    delta_encode_array(values, mask) -> bytes
    bool_encode_array(values) -> bytes
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRCS = [
    os.path.join(_HERE, "codecs.cpp"),
    os.path.join(_HERE, "apply.cpp"),
    os.path.join(_HERE, "extract_batch.cpp"),
]
_SRC = _SRCS[0]

_lib: Optional[ctypes.CDLL] = None
_tried = False


class NativeUnavailable(RuntimeError):
    pass


def _build(lib_path: str) -> bool:
    # compile to a temp path and rename into place: a killed/concurrent
    # build must never leave a partial file at the final (content-hash) name,
    # which would be trusted forever
    tmp = f"{lib_path}.tmp{os.getpid()}"
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        "-o", tmp, *_SRCS,
    ]
    try:
        r = subprocess.run(cmd, capture_output=True, timeout=120)
        if r.returncode != 0 or not os.path.exists(tmp):
            return False
        os.replace(tmp, lib_path)
        return True
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def _lib_name() -> str:
    # the source content hash is baked into the file name, so a stale build
    # of older sources can never be loaded by mistake (these codecs produce
    # the bytes change hashes are computed over — loading stale native code
    # would silently corrupt hashing / the save format)
    h = hashlib.sha256()
    for src in _SRCS:
        with open(src, "rb") as f:
            h.update(f.read())
    return f"_codecs-{h.hexdigest()[:16]}.so"


def _lib_path() -> str:
    # prefer alongside the source; fall back to a per-user cache dir when
    # the package directory is not writable
    name = _lib_name()
    primary = os.path.join(_HERE, name)
    if os.path.exists(primary) or os.access(_HERE, os.W_OK):
        return primary
    cache = os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "automerge_tpu",
    )
    os.makedirs(cache, exist_ok=True)
    return os.path.join(cache, name)


def load() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use. None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if os.environ.get("AUTOMERGE_TPU_NO_NATIVE"):
        return None
    path = _lib_path()
    if not os.path.exists(path) and not _build(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.am_rle_decode_i64.restype = ctypes.c_longlong
    lib.am_rle_decode_i64.argtypes = [u8p, ctypes.c_size_t, ctypes.c_int, i64p, u8p, ctypes.c_size_t]
    lib.am_delta_decode_i64.restype = ctypes.c_longlong
    lib.am_delta_decode_i64.argtypes = [u8p, ctypes.c_size_t, i64p, u8p, ctypes.c_size_t]
    lib.am_bool_decode.restype = ctypes.c_longlong
    lib.am_bool_decode.argtypes = [u8p, ctypes.c_size_t, u8p, ctypes.c_size_t]
    lib.am_rle_encode_i64.restype = ctypes.c_longlong
    lib.am_rle_encode_i64.argtypes = [i64p, u8p, ctypes.c_size_t, ctypes.c_int, u8p, ctypes.c_size_t]
    lib.am_delta_encode_i64.restype = ctypes.c_longlong
    lib.am_delta_encode_i64.argtypes = [i64p, u8p, ctypes.c_size_t, u8p, ctypes.c_size_t, i64p]
    lib.am_bool_encode.restype = ctypes.c_longlong
    lib.am_bool_encode.argtypes = [u8p, ctypes.c_size_t, u8p, ctypes.c_size_t]
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.am_preorder_index.restype = ctypes.c_longlong
    lib.am_preorder_index.argtypes = [i32p, i32p, i32p, ctypes.c_int64, ctypes.c_int64, i32p]
    lib.am_seq_apply.restype = ctypes.c_longlong
    lib.am_seq_apply.argtypes = [
        i64p, i64p, i64p, i32p, i32p, u8p, u8p, i64p, i64p,
        ctypes.c_int64, ctypes.c_int64, i32p, ctypes.c_int64,
    ]
    lib.am_seq_apply_export.restype = ctypes.c_longlong
    lib.am_seq_apply_export.argtypes = [
        i64p, i64p, i64p, i32p, i32p, u8p, u8p, i64p, i64p,
        ctypes.c_int64, i64p, i64p, ctypes.c_int64, i32p, ctypes.c_int64,
    ]
    for name, argtypes in (
        ("am_rle_decode_batch", [u8p, i64p, i64p, i64p, ctypes.c_int64, ctypes.c_int, i64p, u8p]),
        ("am_delta_decode_batch", [u8p, i64p, i64p, i64p, ctypes.c_int64, i64p, u8p]),
        ("am_bool_decode_batch", [u8p, i64p, i64p, i64p, ctypes.c_int64, u8p]),
        ("am_rle_decode_batch_strtab", [u8p, i64p, i64p, i64p, ctypes.c_int64, i32p, i64p, i64p, ctypes.c_int64]),
        ("am_leb_decode_rows", [u8p, ctypes.c_int64, i64p, i64p, i32p, ctypes.c_int64, i64p]),
    ):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_longlong
        fn.argtypes = argtypes
    _lib = lib
    return _lib


def available() -> bool:
    return load() is not None


def _u8(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _i64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _inbuf(buf: bytes) -> np.ndarray:
    return np.frombuffer(buf, dtype=np.uint8) if len(buf) else np.zeros(1, np.uint8)


def rle_decode_array(buf: bytes, signed_vals: bool, capacity: int) -> Tuple[np.ndarray, np.ndarray]:
    lib = load()
    if lib is None:
        raise NativeUnavailable("native codecs not available")
    vals = np.empty(capacity, np.int64)
    mask = np.empty(capacity, np.uint8)
    b = _inbuf(buf)
    n = lib.am_rle_decode_i64(_u8(b), len(buf), int(signed_vals), _i64(vals), _u8(mask), capacity)
    if n < 0:
        raise ValueError("malformed RLE column")
    return vals[:n], mask[:n].astype(bool)


def delta_decode_array(buf: bytes, capacity: int) -> Tuple[np.ndarray, np.ndarray]:
    lib = load()
    if lib is None:
        raise NativeUnavailable("native codecs not available")
    vals = np.empty(capacity, np.int64)
    mask = np.empty(capacity, np.uint8)
    b = _inbuf(buf)
    n = lib.am_delta_decode_i64(_u8(b), len(buf), _i64(vals), _u8(mask), capacity)
    if n < 0:
        raise ValueError("malformed delta column")
    return vals[:n], mask[:n].astype(bool)


def bool_decode_array(buf: bytes, capacity: int) -> np.ndarray:
    lib = load()
    if lib is None:
        raise NativeUnavailable("native codecs not available")
    out = np.empty(capacity, np.uint8)
    b = _inbuf(buf)
    n = lib.am_bool_decode(_u8(b), len(buf), _u8(out), capacity)
    if n < 0:
        raise ValueError("malformed boolean column")
    return out[:n].astype(bool)


def rle_encode_array(values: np.ndarray, mask: np.ndarray, signed_vals: bool) -> bytes:
    lib = load()
    if lib is None:
        raise NativeUnavailable("native codecs not available")
    values = np.ascontiguousarray(values, np.int64)
    m = np.ascontiguousarray(mask, np.uint8)
    n = len(values)
    out = np.empty(12 * n + 32, np.uint8)
    w = lib.am_rle_encode_i64(_i64(values), _u8(m), n, int(signed_vals), _u8(out), len(out))
    if w < 0:
        raise ValueError("rle encode: output overflow")
    return out[:w].tobytes()


def delta_encode_array(values: np.ndarray, mask: np.ndarray) -> bytes:
    lib = load()
    if lib is None:
        raise NativeUnavailable("native codecs not available")
    values = np.ascontiguousarray(values, np.int64)
    m = np.ascontiguousarray(mask, np.uint8)
    n = len(values)
    out = np.empty(12 * n + 32, np.uint8)
    scratch = np.empty(max(n, 1), np.int64)
    w = lib.am_delta_encode_i64(_i64(values), _u8(m), n, _u8(out), len(out), _i64(scratch))
    if w < 0:
        raise ValueError("delta encode: output overflow")
    return out[:w].tobytes()


def bool_encode_array(values: np.ndarray) -> bytes:
    lib = load()
    if lib is None:
        raise NativeUnavailable("native codecs not available")
    v = np.ascontiguousarray(values, np.uint8)
    n = len(v)
    out = np.empty(11 * n + 32, np.uint8)
    w = lib.am_bool_encode(_u8(v), n, _u8(out), len(out))
    if w < 0:
        raise ValueError("bool encode: output overflow")
    return out[:w].tobytes()


def _i32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def seq_apply(
    op_id: np.ndarray,
    obj: np.ndarray,
    elem: np.ndarray,
    prop: np.ndarray,
    action: np.ndarray,
    insert: np.ndarray,
    is_counter: np.ndarray,
    pred_off: np.ndarray,
    pred_flat: np.ndarray,
    query_obj: int,
) -> np.ndarray:
    """Sequential per-op apply (native); returns the queried sequence
    object's visible winner rows in document order.

    The measured stand-in for the reference's sequential ``apply_changes``
    (automerge.rs:1258-1280) — the baseline the batched device merge is
    compared against, and an independent oracle for its results.
    """
    lib = load()
    if lib is None:
        raise NativeUnavailable("native codecs not available")
    n = len(op_id)
    op_id = np.ascontiguousarray(op_id, np.int64)
    obj = np.ascontiguousarray(obj, np.int64)
    elem = np.ascontiguousarray(elem, np.int64)
    prop = np.ascontiguousarray(prop, np.int32)
    action = np.ascontiguousarray(action, np.int32)
    insert = np.ascontiguousarray(insert, np.uint8)
    is_counter = np.ascontiguousarray(is_counter, np.uint8)
    pred_off = np.ascontiguousarray(pred_off, np.int64)
    pred_flat = (
        np.ascontiguousarray(pred_flat, np.int64)
        if len(pred_flat)
        else np.zeros(1, np.int64)
    )
    out = np.empty(max(n, 1), np.int32)
    r = lib.am_seq_apply(
        _i64(op_id), _i64(obj), _i64(elem), _i32(prop), _i32(action),
        _u8(insert), _u8(is_counter), _i64(pred_off), _i64(pred_flat),
        n, int(query_obj), _i32(out), len(out),
    )
    if r < 0:
        raise ValueError(f"sequential apply failed (code {r})")
    return out[:r]


def seq_apply_export(
    op_id, obj, elem, prop, action, insert, is_counter, pred_off, pred_flat
):
    """Sequential apply + full RGA element-order export.

    Returns (obj_keys int64[k], obj_off int64[k+1], elem_rows int32[...]):
    every sequence object's elements (insert-op rows) in document order,
    tombstones included — the input the host op-store bulk loader needs.
    """
    lib = load()
    if lib is None:
        raise NativeUnavailable("native codecs not available")
    n = len(op_id)
    op_id = np.ascontiguousarray(op_id, np.int64)
    obj = np.ascontiguousarray(obj, np.int64)
    elem = np.ascontiguousarray(elem, np.int64)
    prop = np.ascontiguousarray(prop, np.int32)
    action = np.ascontiguousarray(action, np.int32)
    insert = np.ascontiguousarray(insert, np.uint8)
    is_counter = np.ascontiguousarray(is_counter, np.uint8)
    pred_off = np.ascontiguousarray(pred_off, np.int64)
    pred_flat = (
        np.ascontiguousarray(pred_flat, np.int64)
        if len(pred_flat)
        else np.zeros(1, np.int64)
    )
    obj_cap = n + 2
    obj_keys = np.empty(obj_cap, np.int64)
    obj_off = np.empty(obj_cap + 1, np.int64)
    elem_rows = np.empty(max(n, 1), np.int32)
    k = lib.am_seq_apply_export(
        _i64(op_id), _i64(obj), _i64(elem), _i32(prop), _i32(action),
        _u8(insert), _u8(is_counter), _i64(pred_off), _i64(pred_flat),
        n, _i64(obj_keys), _i64(obj_off), obj_cap, _i32(elem_rows), len(elem_rows),
    )
    if k < 0:
        raise ValueError(f"sequential apply failed (code {k})")
    return obj_keys[:k], obj_off[: k + 1], elem_rows[: int(obj_off[k])]


def preorder_available() -> bool:
    lib = load()
    return lib is not None and hasattr(lib, "am_preorder_index")


def preorder_index(
    first_child: np.ndarray, next_sib: np.ndarray, parent: np.ndarray, P: int
) -> np.ndarray:
    """Document-order index per element node via the native preorder walk."""
    lib = load()
    if lib is None:
        raise NativeUnavailable("native codecs not available")
    fc = np.ascontiguousarray(first_child, np.int32)
    ns = np.ascontiguousarray(next_sib, np.int32)
    pa = np.ascontiguousarray(parent, np.int32)
    N = len(fc)
    out = np.empty(P, np.int32)
    r = lib.am_preorder_index(_i32(fc), _i32(ns), _i32(pa), P, N, _i32(out))
    if r < 0:
        raise ValueError("cyclic element structure in preorder walk")
    return out
