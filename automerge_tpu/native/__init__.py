"""Native codec loader: compiles codecs.cpp on first use, loads via ctypes.

The reference's storage layer is native (Rust); ours keeps the byte-hot
columnar codec loops in C++ with a pure-Python fallback (utils/codecs.py)
when no compiler is available. Set AUTOMERGE_TPU_NO_NATIVE=1 to force the
fallback.

Array-level API (numpy in/out):
    rle_decode_array(buf, signed_vals, capacity) -> (values i64, mask bool)
    delta_decode_array(buf, capacity) -> (values, mask)
    bool_decode_array(buf, capacity) -> bool array
    rle_encode_array(values, mask, signed_vals) -> bytes
    delta_encode_array(values, mask) -> bytes
    bool_encode_array(values) -> bytes
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRCS = [
    os.path.join(_HERE, "codecs.cpp"),
    os.path.join(_HERE, "apply.cpp"),
    os.path.join(_HERE, "extract_batch.cpp"),
    os.path.join(_HERE, "session.cpp"),
    os.path.join(_HERE, "map_session.cpp"),
    os.path.join(_HERE, "merge_cols.cpp"),
    os.path.join(_HERE, "assemble.cpp"),
    os.path.join(_HERE, "condense.cpp"),
]
_SRC = _SRCS[0]

_lib: Optional[ctypes.CDLL] = None
_tried = False


class NativeUnavailable(RuntimeError):
    pass


def _prune_stale(dirname: str, prefix: str, keep: str) -> None:
    """Remove superseded content-hash builds so artifacts don't accumulate
    (only files matching ``prefix``*.so other than ``keep``)."""
    try:
        for name in os.listdir(dirname):
            if name.startswith(prefix) and name.endswith(".so") and name != keep:
                try:
                    os.remove(os.path.join(dirname, name))
                except OSError:
                    pass
    except OSError:
        pass


def _build(lib_path: str) -> bool:
    # compile to a temp path and rename into place: a killed/concurrent
    # build must never leave a partial file at the final (content-hash) name,
    # which would be trusted forever.
    # -march=native first (vectorizing the column loops measured ~15% on the
    # assembler/merge hot paths; the cache name is ISA-keyed, see
    # _lib_name), portable -O2 as the fallback for exotic toolchains.
    tmp = f"{lib_path}.tmp{os.getpid()}"
    try:
        for opt in (["-O3", "-march=native"], ["-O2"]):
            cmd = ["g++", *opt, "-shared", "-fPIC", "-std=c++17",
                   "-o", tmp, *_SRCS]
            try:
                r = subprocess.run(cmd, capture_output=True, timeout=120)
            except (OSError, subprocess.TimeoutExpired):
                continue
            if r.returncode != 0 or not os.path.exists(tmp):
                continue
            os.replace(tmp, lib_path)
            # prune only the package-local dir: the XDG cache fallback is
            # shared across checkouts/venvs whose source hashes differ —
            # deleting siblings there would ping-pong rebuilds between them
            if os.path.dirname(lib_path) == _HERE:
                _prune_stale(_HERE, "_codecs-", os.path.basename(lib_path))
            return True
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def _lib_name() -> str:
    # the source content hash is baked into the file name, so a stale build
    # of older sources can never be loaded by mistake (these codecs produce
    # the bytes change hashes are computed over — loading stale native code
    # would silently corrupt hashing / the save format)
    h = hashlib.sha256()
    h.update(b"flags:o3-native-v1")  # compile flags key the cache too
    # -march=native binaries are host-ISA-specific; key the cache by the
    # CPU's feature set so a shared cache dir (NFS $HOME, moved container
    # volumes) never hands an AVX-512 build to a host without it
    try:
        with open("/proc/cpuinfo", "rb") as f:
            for line in f:
                if line.startswith((b"flags", b"Features")):
                    h.update(line)
                    break
    except OSError:
        import platform

        h.update(platform.machine().encode())
    for src in _SRCS:
        with open(src, "rb") as f:
            h.update(f.read())
    return f"_codecs-{h.hexdigest()[:16]}.so"


def _lib_path() -> str:
    # prefer alongside the source; fall back to a per-user cache dir when
    # the package directory is not writable
    name = _lib_name()
    primary = os.path.join(_HERE, name)
    if os.path.exists(primary) or os.access(_HERE, os.W_OK):
        return primary
    cache = os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "automerge_tpu",
    )
    os.makedirs(cache, exist_ok=True)
    return os.path.join(cache, name)


def _tune_allocator() -> None:
    """Keep large freed buffers on the heap instead of munmap'ing them.

    numpy frees the multi-MB merge/assemble output arrays between calls;
    glibc's default mmap threshold returns those pages to the kernel, so
    every merge re-faults ~30MB (~10ms measured — comparable to the whole
    native merge). Raising M_MMAP_THRESHOLD / M_TRIM_THRESHOLD keeps the
    pages resident and cuts steady-state array first-touch cost ~5x.
    Costs: higher retained RSS. Opt out with AUTOMERGE_TPU_NO_MALLOPT=1."""
    if os.environ.get("AUTOMERGE_TPU_NO_MALLOPT"):
        return
    try:
        libc = ctypes.CDLL(None)
        M_MMAP_THRESHOLD, M_TRIM_THRESHOLD = -3, -1
        libc.mallopt(M_MMAP_THRESHOLD, 1 << 30)
        libc.mallopt(M_TRIM_THRESHOLD, 1 << 30)
    except (OSError, AttributeError):
        pass  # non-glibc platforms: no-op


def load() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use. None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if os.environ.get("AUTOMERGE_TPU_NO_NATIVE"):
        return None
    _tune_allocator()
    path = _lib_path()
    if not os.path.exists(path) and not _build(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.am_rle_decode_i64.restype = ctypes.c_longlong
    lib.am_rle_decode_i64.argtypes = [u8p, ctypes.c_size_t, ctypes.c_int, i64p, u8p, ctypes.c_size_t]
    lib.am_delta_decode_i64.restype = ctypes.c_longlong
    lib.am_delta_decode_i64.argtypes = [u8p, ctypes.c_size_t, i64p, u8p, ctypes.c_size_t]
    lib.am_bool_decode.restype = ctypes.c_longlong
    lib.am_bool_decode.argtypes = [u8p, ctypes.c_size_t, u8p, ctypes.c_size_t]
    lib.am_rle_encode_i64.restype = ctypes.c_longlong
    lib.am_rle_encode_i64.argtypes = [i64p, u8p, ctypes.c_size_t, ctypes.c_int, u8p, ctypes.c_size_t]
    lib.am_delta_encode_i64.restype = ctypes.c_longlong
    lib.am_delta_encode_i64.argtypes = [i64p, u8p, ctypes.c_size_t, u8p, ctypes.c_size_t, i64p]
    lib.am_bool_encode.restype = ctypes.c_longlong
    lib.am_bool_encode.argtypes = [u8p, ctypes.c_size_t, u8p, ctypes.c_size_t]
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.am_preorder_index.restype = ctypes.c_longlong
    lib.am_preorder_index.argtypes = [i32p, i32p, i32p, ctypes.c_int64, ctypes.c_int64, i32p]
    lib.am_seq_apply.restype = ctypes.c_longlong
    lib.am_seq_apply.argtypes = [
        i64p, i64p, i64p, i32p, i32p, u8p, u8p, i64p, i64p,
        ctypes.c_int64, ctypes.c_int64, i32p, ctypes.c_int64,
    ]
    lib.am_seq_apply_export.restype = ctypes.c_longlong
    lib.am_seq_apply_export.argtypes = [
        i64p, i64p, i64p, i32p, i32p, u8p, u8p, i64p, i64p,
        ctypes.c_int64, i64p, i64p, ctypes.c_int64, i32p, ctypes.c_int64,
    ]
    for name, argtypes in (
        ("am_rle_decode_batch", [u8p, i64p, i64p, i64p, ctypes.c_int64, ctypes.c_int, i64p, u8p]),
        ("am_delta_decode_batch", [u8p, i64p, i64p, i64p, ctypes.c_int64, i64p, u8p]),
        ("am_bool_decode_batch", [u8p, i64p, i64p, i64p, ctypes.c_int64, u8p]),
        ("am_rle_decode_batch_strtab", [u8p, i64p, i64p, i64p, ctypes.c_int64, i32p, i64p, i64p, ctypes.c_int64]),
        ("am_leb_decode_rows", [u8p, ctypes.c_int64, i64p, i64p, i32p, ctypes.c_int64, i64p]),
    ):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_longlong
        fn.argtypes = argtypes
    lib.am_rle_encode_strtab.restype = ctypes.c_longlong
    lib.am_rle_encode_strtab.argtypes = [
        i64p, ctypes.c_int64, i64p, i64p, u8p, u8p, ctypes.c_int64,
    ]
    lib.am_join_rows_i64.restype = ctypes.c_longlong
    lib.am_join_rows_i64.argtypes = [
        i64p, ctypes.c_int64, i64p, ctypes.c_int64, ctypes.c_int32, i32p,
    ]
    lib.am_chain_condense.restype = ctypes.c_longlong
    lib.am_chain_condense.argtypes = [
        i32p, i32p, i32p, u8p, ctypes.c_int64, ctypes.c_int64,
        i32p, i32p, i32p, i32p, i32p, i32p, i32p, i32p,
    ]
    lib.am_assemble_log.restype = ctypes.c_longlong
    lib.am_assemble_log.argtypes = [
        # per-change metadata (11 i64 arrays), col_ptrs, n_changes
        i64p, i64p, i64p, i64p, i64p, i64p, i64p, i64p, i64p, i64p, i64p,
        i64p, ctypes.c_int64,
        # translation tables + actor_bits + global const-fill directives
        # + per-change const shortcut tables (obj key, key sid)
        i64p, i32p, i32p, ctypes.c_int32, i64p, i64p, i64p, i64p,
        # row outputs
        i64p, i64p, i32p, i32p, u8p, u8p, i32p, i64p, i32p, i32p, i32p,
        i64p, i64p, i32p, i32p, ctypes.c_int64,
        # pred outputs
        i32p, i32p, ctypes.c_int64,
        # obj table + meta
        i64p, i64p,
    ]
    lib.am_merge_cols.restype = ctypes.c_longlong
    lib.am_merge_cols.argtypes = [
        i32p, u8p, i32p, i32p, i32p, i32p, i32p, i32p, u8p, ctypes.c_int64,
        i32p, i32p, ctypes.c_int64, ctypes.c_int64,
        u8p, i32p, i32p, i32p, i32p, i32p, i32p, i32p, i32p, u8p, i32p,
        i32p, i32p, ctypes.c_int32,
    ]
    vp = ctypes.c_void_p
    lib.am_edit_create.restype = vp
    lib.am_edit_create.argtypes = [ctypes.c_int64]
    lib.am_edit_destroy.restype = None
    lib.am_edit_destroy.argtypes = [vp]
    for name, argtypes in (
        ("am_edit_init", [vp, i64p, i64p, i32p, ctypes.c_int64]),
        ("am_edit_length", [vp]),
        ("am_edit_op_count", [vp]),
        ("am_edit_splice", [vp, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, i32p, i32p, ctypes.c_int64]),
        ("am_edit_splice_batch", [vp, ctypes.c_int64, i64p, i64p, i64p, i32p, i32p, ctypes.c_int64, ctypes.c_uint8]),
        ("am_edit_export", [vp, ctypes.c_int64, i64p, i64p, i64p, i32p, i32p, u8p]),
        ("am_edit_order", [vp, i64p, ctypes.c_int64]),
    ):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_longlong
        fn.argtypes = argtypes
    lib.am_map_create.restype = vp
    lib.am_map_create.argtypes = [ctypes.c_int64]
    lib.am_map_destroy.restype = None
    lib.am_map_destroy.argtypes = [vp]
    for name, argtypes in (
        ("am_map_init", [vp, u8p, i64p, i64p, ctypes.c_int64]),
        ("am_map_op_count", [vp]),
        ("am_map_put", [vp, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
                        ctypes.c_int32, ctypes.c_int64, ctypes.c_double, u8p,
                        ctypes.c_int64]),
        ("am_map_export_sizes", [vp, ctypes.c_int64, i64p, i64p]),
        ("am_map_export", [vp, ctypes.c_int64, i64p, i64p, i64p, i64p, u8p]),
        ("am_map_keytab_sizes", [vp, i64p, i64p]),
        ("am_map_keytab", [vp, u8p, i64p]),
    ):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_longlong
        fn.argtypes = argtypes
    _lib = lib
    return _lib


_fastcall = None
_fastcall_tried = False


def fastcall():
    """The CPython fast-call extension (fastcall.c), building it on first
    use; None if unavailable. Its splice() entry bypasses ctypes' ~1us
    per-call marshalling on the per-edit hot path."""
    global _fastcall, _fastcall_tried
    if _fastcall is not None or _fastcall_tried:
        return _fastcall
    _fastcall_tried = True
    lib = load()
    if lib is None:
        return None
    import sys
    import sysconfig

    src = os.path.join(_HERE, "fastcall.c")
    h = hashlib.sha256()
    with open(src, "rb") as f:
        h.update(f.read())
    # unlike the pure-C codecs .so, this links against Python.h internals
    # (PyUnicode object layout) — the interpreter ABI tag must key the
    # cache or a module built under one CPython silently corrupts another
    tag = sys.implementation.cache_tag or "py"
    name = f"_am_fastcall-{tag}-{h.hexdigest()[:16]}.so"
    path = os.path.join(os.path.dirname(_lib_path()), name)
    if not os.path.exists(path):
        tmp = f"{path}.tmp{os.getpid()}"
        inc = sysconfig.get_path("include")
        cmd = ["gcc", "-O2", "-shared", "-fPIC", f"-I{inc}", "-o", tmp, src]
        try:
            r = subprocess.run(cmd, capture_output=True, timeout=120)
            if r.returncode != 0 or not os.path.exists(tmp):
                return None
            os.replace(tmp, path)
            if os.path.dirname(path) == _HERE:
                _prune_stale(_HERE, "_am_fastcall-", os.path.basename(path))
        except (OSError, subprocess.TimeoutExpired):
            return None
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
    try:
        import importlib.util

        spec = importlib.util.spec_from_file_location("am_fastcall", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.setup(ctypes.cast(lib.am_edit_splice, ctypes.c_void_p).value)
        mod.setup_map(ctypes.cast(lib.am_map_put, ctypes.c_void_p).value)
        _fastcall = mod
    except Exception:
        return None
    return _fastcall


def available() -> bool:
    return load() is not None


def _u8(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _i64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _inbuf(buf: bytes) -> np.ndarray:
    return np.frombuffer(buf, dtype=np.uint8) if len(buf) else np.zeros(1, np.uint8)


def rle_decode_array(buf: bytes, signed_vals: bool, capacity: int) -> Tuple[np.ndarray, np.ndarray]:
    lib = load()
    if lib is None:
        raise NativeUnavailable("native codecs not available")
    vals = np.empty(capacity, np.int64)
    mask = np.empty(capacity, np.uint8)
    b = _inbuf(buf)
    n = lib.am_rle_decode_i64(_u8(b), len(buf), int(signed_vals), _i64(vals), _u8(mask), capacity)
    if n < 0:
        raise ValueError("malformed RLE column")
    return vals[:n], mask[:n].astype(bool)


def delta_decode_array(buf: bytes, capacity: int) -> Tuple[np.ndarray, np.ndarray]:
    lib = load()
    if lib is None:
        raise NativeUnavailable("native codecs not available")
    vals = np.empty(capacity, np.int64)
    mask = np.empty(capacity, np.uint8)
    b = _inbuf(buf)
    n = lib.am_delta_decode_i64(_u8(b), len(buf), _i64(vals), _u8(mask), capacity)
    if n < 0:
        raise ValueError("malformed delta column")
    return vals[:n], mask[:n].astype(bool)


def bool_decode_array(buf: bytes, capacity: int) -> np.ndarray:
    lib = load()
    if lib is None:
        raise NativeUnavailable("native codecs not available")
    out = np.empty(capacity, np.uint8)
    b = _inbuf(buf)
    n = lib.am_bool_decode(_u8(b), len(buf), _u8(out), capacity)
    if n < 0:
        raise ValueError("malformed boolean column")
    return out[:n].astype(bool)


def rle_encode_array(values: np.ndarray, mask: np.ndarray, signed_vals: bool) -> bytes:
    lib = load()
    if lib is None:
        raise NativeUnavailable("native codecs not available")
    values = np.ascontiguousarray(values, np.int64)
    m = np.ascontiguousarray(mask, np.uint8)
    n = len(values)
    out = np.empty(12 * n + 32, np.uint8)
    w = lib.am_rle_encode_i64(_i64(values), _u8(m), n, int(signed_vals), _u8(out), len(out))
    if w < 0:
        raise ValueError("rle encode: output overflow")
    return out[:w].tobytes()


def delta_encode_array(values: np.ndarray, mask: np.ndarray) -> bytes:
    lib = load()
    if lib is None:
        raise NativeUnavailable("native codecs not available")
    values = np.ascontiguousarray(values, np.int64)
    m = np.ascontiguousarray(mask, np.uint8)
    n = len(values)
    out = np.empty(12 * n + 32, np.uint8)
    scratch = np.empty(max(n, 1), np.int64)
    w = lib.am_delta_encode_i64(_i64(values), _u8(m), n, _u8(out), len(out), _i64(scratch))
    if w < 0:
        raise ValueError("delta encode: output overflow")
    return out[:w].tobytes()


def bool_encode_array(values: np.ndarray) -> bytes:
    lib = load()
    if lib is None:
        raise NativeUnavailable("native codecs not available")
    v = np.ascontiguousarray(values, np.uint8)
    n = len(v)
    out = np.empty(11 * n + 32, np.uint8)
    w = lib.am_bool_encode(_u8(v), n, _u8(out), len(out))
    if w < 0:
        raise ValueError("bool encode: output overflow")
    return out[:w].tobytes()


def _i32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def seq_apply(
    op_id: np.ndarray,
    obj: np.ndarray,
    elem: np.ndarray,
    prop: np.ndarray,
    action: np.ndarray,
    insert: np.ndarray,
    is_counter: np.ndarray,
    pred_off: np.ndarray,
    pred_flat: np.ndarray,
    query_obj: int,
) -> np.ndarray:
    """Sequential per-op apply (native); returns the queried sequence
    object's visible winner rows in document order.

    The measured stand-in for the reference's sequential ``apply_changes``
    (automerge.rs:1258-1280) — the baseline the batched device merge is
    compared against, and an independent oracle for its results.
    """
    lib = load()
    if lib is None:
        raise NativeUnavailable("native codecs not available")
    n = len(op_id)
    op_id = np.ascontiguousarray(op_id, np.int64)
    obj = np.ascontiguousarray(obj, np.int64)
    elem = np.ascontiguousarray(elem, np.int64)
    prop = np.ascontiguousarray(prop, np.int32)
    action = np.ascontiguousarray(action, np.int32)
    insert = np.ascontiguousarray(insert, np.uint8)
    is_counter = np.ascontiguousarray(is_counter, np.uint8)
    pred_off = np.ascontiguousarray(pred_off, np.int64)
    pred_flat = (
        np.ascontiguousarray(pred_flat, np.int64)
        if len(pred_flat)
        else np.zeros(1, np.int64)
    )
    out = np.empty(max(n, 1), np.int32)
    r = lib.am_seq_apply(
        _i64(op_id), _i64(obj), _i64(elem), _i32(prop), _i32(action),
        _u8(insert), _u8(is_counter), _i64(pred_off), _i64(pred_flat),
        n, int(query_obj), _i32(out), len(out),
    )
    if r < 0:
        raise ValueError(f"sequential apply failed (code {r})")
    return out[:r]


def seq_apply_export(
    op_id, obj, elem, prop, action, insert, is_counter, pred_off, pred_flat
):
    """Sequential apply + full RGA element-order export.

    Returns (obj_keys int64[k], obj_off int64[k+1], elem_rows int32[...]):
    every sequence object's elements (insert-op rows) in document order,
    tombstones included — the input the host op-store bulk loader needs.
    """
    lib = load()
    if lib is None:
        raise NativeUnavailable("native codecs not available")
    n = len(op_id)
    op_id = np.ascontiguousarray(op_id, np.int64)
    obj = np.ascontiguousarray(obj, np.int64)
    elem = np.ascontiguousarray(elem, np.int64)
    prop = np.ascontiguousarray(prop, np.int32)
    action = np.ascontiguousarray(action, np.int32)
    insert = np.ascontiguousarray(insert, np.uint8)
    is_counter = np.ascontiguousarray(is_counter, np.uint8)
    pred_off = np.ascontiguousarray(pred_off, np.int64)
    pred_flat = (
        np.ascontiguousarray(pred_flat, np.int64)
        if len(pred_flat)
        else np.zeros(1, np.int64)
    )
    obj_cap = n + 2
    obj_keys = np.empty(obj_cap, np.int64)
    obj_off = np.empty(obj_cap + 1, np.int64)
    elem_rows = np.empty(max(n, 1), np.int32)
    k = lib.am_seq_apply_export(
        _i64(op_id), _i64(obj), _i64(elem), _i32(prop), _i32(action),
        _u8(insert), _u8(is_counter), _i64(pred_off), _i64(pred_flat),
        n, _i64(obj_keys), _i64(obj_off), obj_cap, _i32(elem_rows), len(elem_rows),
    )
    if k < 0:
        raise ValueError(f"sequential apply failed (code {k})")
    return obj_keys[:k], obj_off[: k + 1], elem_rows[: int(obj_off[k])]


def rle_encode_strtab(ids: np.ndarray, table) -> bytes:
    """String RLE column from an int-id column (-1 = null) + string table;
    byte-identical to RleEncoder("str") over table lookups. Raises
    NativeUnavailable when the lib is absent."""
    lib = load()
    if lib is None or not hasattr(lib, "am_rle_encode_strtab"):
        raise NativeUnavailable("native strtab encode not available")
    ids = np.ascontiguousarray(ids, np.int64)
    n = len(ids)
    raws = [s.encode("utf-8") for s in table]
    tab_len = np.asarray([len(r) for r in raws] or [0], np.int64)
    tab_off = np.concatenate([[0], np.cumsum(tab_len)]).astype(np.int64)
    tab_buf = _inbuf(b"".join(raws))
    max_len = int(tab_len.max()) if len(raws) else 0
    cap = n * (11 + max_len) + 32
    if cap > (1 << 27):  # degenerate giant-string tables: python fallback
        raise NativeUnavailable("strtab encode capacity too large")
    out = np.empty(cap, np.uint8)
    w = lib.am_rle_encode_strtab(
        _i64(ids), n, _i64(tab_off), _i64(tab_len), _u8(tab_buf), _u8(out), cap
    )
    if w < 0:
        raise ValueError("strtab encode: output overflow")
    return out[:w].tobytes()


def join_rows(sorted_keys: np.ndarray, queries: np.ndarray, missing: int) -> np.ndarray:
    """out[i] = row of queries[i] in the sorted key column, else ``missing``
    (multithreaded native binary search). Raises NativeUnavailable."""
    lib = load()
    if lib is None or not hasattr(lib, "am_join_rows_i64"):
        raise NativeUnavailable("native join not available")
    s = np.ascontiguousarray(sorted_keys, np.int64)
    q = np.ascontiguousarray(queries, np.int64)
    out = np.empty(max(len(q), 1), np.int32)
    lib.am_join_rows_i64(_i64(s), len(s), _i64(q), len(q), missing, _i32(out))
    return out[: len(q)]


def merge_available() -> bool:
    lib = load()
    return lib is not None and hasattr(lib, "am_merge_cols")


def merge_cols(cols, n_objs: int, want_elem_index: bool = True):
    """Host columnar merge (merge_cols.cpp): the native engine producing the
    same output arrays as the jax merge kernel from the same padded columns.

    Returns the full output dict (ops/merge.py ALL_OUTPUTS); callers select
    what they need. ``want_elem_index=False`` skips the preorder walk (the
    only random-access pass; elem_index comes back all -1) for fetches that
    exclude document order. Raises NativeUnavailable without the lib."""
    lib = load()
    if lib is None:
        raise NativeUnavailable("native merge not available")
    action = np.ascontiguousarray(cols["action"], np.int32)
    insert = np.ascontiguousarray(cols["insert"], np.uint8)
    prop = np.ascontiguousarray(cols["prop"], np.int32)
    elem_ref = np.ascontiguousarray(cols["elem_ref"], np.int32)
    obj_dense = np.ascontiguousarray(cols["obj_dense"], np.int32)
    value_tag = np.ascontiguousarray(cols["value_tag"], np.int32)
    value_i32 = np.ascontiguousarray(cols["value_i32"], np.int32)
    width = np.ascontiguousarray(cols["width"], np.int32)
    covered = np.ascontiguousarray(cols["covered"], np.uint8)
    pred_src = np.ascontiguousarray(cols["pred_src"], np.int32)
    pred_tgt = np.ascontiguousarray(cols["pred_tgt"], np.int32)
    P = len(action)
    Q = len(pred_src)
    N = 2 * P + 3
    n_objs2 = n_objs + 2
    out = {
        "visible": np.empty(P, np.uint8),
        "counter_inc": np.empty(P, np.int32),
        "winner": np.empty(P, np.int32),
        "conflicts": np.empty(P, np.int32),
        "succ_count": np.empty(P, np.int32),
        "inc_count": np.empty(P, np.int32),
        "first_child": np.empty(N, np.int32),
        "next_sib": np.empty(N, np.int32),
        "parent_row": np.empty(P, np.int32),
        "is_elem": np.empty(P, np.uint8),
        "obj_vis_len": np.empty(n_objs2, np.int32),
        "obj_text_width": np.empty(n_objs2, np.int32),
        "elem_index": np.empty(P, np.int32),
    }
    r = lib.am_merge_cols(
        _i32(action), _u8(insert), _i32(prop), _i32(elem_ref), _i32(obj_dense),
        _i32(value_tag), _i32(value_i32), _i32(width), _u8(covered), P,
        _i32(pred_src), _i32(pred_tgt), Q, n_objs,
        _u8(out["visible"]), _i32(out["counter_inc"]), _i32(out["winner"]),
        _i32(out["conflicts"]), _i32(out["succ_count"]), _i32(out["inc_count"]),
        _i32(out["first_child"]), _i32(out["next_sib"]),
        _i32(out["parent_row"]), _u8(out["is_elem"]),
        _i32(out["obj_vis_len"]), _i32(out["obj_text_width"]),
        _i32(out["elem_index"]), int(bool(want_elem_index)),
    )
    if r < 0:
        raise ValueError("native merge failed (cyclic element structure)")
    out["visible"] = out["visible"].astype(bool)
    out["is_elem"] = out["is_elem"].astype(bool)
    return out


def preorder_available() -> bool:
    lib = load()
    return lib is not None and hasattr(lib, "am_preorder_index")


def preorder_index(
    first_child: np.ndarray, next_sib: np.ndarray, parent: np.ndarray, P: int
) -> np.ndarray:
    """Document-order index per element node via the native preorder walk."""
    lib = load()
    if lib is None:
        raise NativeUnavailable("native codecs not available")
    fc = np.ascontiguousarray(first_child, np.int32)
    ns = np.ascontiguousarray(next_sib, np.int32)
    pa = np.ascontiguousarray(parent, np.int32)
    N = len(fc)
    out = np.empty(P, np.int32)
    r = lib.am_preorder_index(_i32(fc), _i32(ns), _i32(pa), P, N, _i32(out))
    if r < 0:
        raise ValueError("cyclic element structure in preorder walk")
    return out


def chain_condense(
    first_child: np.ndarray, next_sib: np.ndarray, parent: np.ndarray,
    is_elem: np.ndarray, P: int, n_objs: int,
):
    """Collapse first-child chains of the sibling forest (condense.cpp).

    Returns (R, per-element {chain_id, offset}, per-chain {head, len,
    tail_ans, cpar, centry} trimmed to R, start_chain[n_objs]). The
    condensed graph is what the mesh ranks with O(R) collectives per
    doubling step (parallel/sharding.py)."""
    lib = load()
    if lib is None or not hasattr(lib, "am_chain_condense"):
        raise NativeUnavailable("native condense not available")
    fc = np.ascontiguousarray(first_child, np.int32)
    ns = np.ascontiguousarray(next_sib, np.int32)
    pa = np.ascontiguousarray(parent, np.int32)
    ie = np.ascontiguousarray(is_elem, np.uint8)
    chain_id = np.empty(max(P, 1), np.int32)
    offset = np.empty(max(P, 1), np.int32)
    head = np.empty(max(P, 1), np.int32)
    length = np.empty(max(P, 1), np.int32)
    tail_ans = np.empty(max(P, 1), np.int32)
    cpar = np.empty(max(P, 1), np.int32)
    centry = np.empty(max(P, 1), np.int32)
    start_chain = np.empty(max(n_objs, 1), np.int32)
    R = lib.am_chain_condense(
        _i32(fc), _i32(ns), _i32(pa), _u8(ie), P, n_objs,
        _i32(chain_id), _i32(offset), _i32(head), _i32(length),
        _i32(tail_ans), _i32(cpar), _i32(centry), _i32(start_chain),
    )
    if R < 0:
        raise ValueError("cyclic element structure in chain condensation")
    R = int(R)
    return R, {
        "chain_id": chain_id[:P],
        "offset": offset[:P],
        "head": head[:R],
        "len": length[:R],
        "tail_ans": tail_ans[:R],
        "cpar": cpar[:R],
        "centry": centry[:R],
        "start_chain": start_chain[:n_objs],
    }


def _splice_error(code: int):
    """Session splice failures carry the same typed error and wording as
    the python transaction path (errors.AutomergeError)."""
    from ..errors import AutomergeError

    if code == -2:
        return AutomergeError("splice: delete past end of sequence")
    return AutomergeError("splice: index out of bounds")


def _cp_widths(cps: np.ndarray) -> np.ndarray:
    """Per-codepoint text widths for the configured encoding
    (reference: text_value.rs width-per-encoding)."""
    from ..types import get_text_encoding

    enc = get_text_encoding()
    if enc == "utf16":
        return np.where(cps > 0xFFFF, 2, 1).astype(np.int32)
    if enc == "utf8":
        return (
            1
            + (cps > 0x7F).astype(np.int32)
            + (cps > 0x7FF).astype(np.int32)
            + (cps > 0xFFFF).astype(np.int32)
        ).astype(np.int32)
    return np.ones(len(cps), np.int32)


class EditSession:
    """The native text-edit session (session.cpp): owns one text object's
    visible-element state inside a transaction; splices resolve in C++."""

    __slots__ = ("_lib", "_h", "_splice_fn", "_len_fn", "_one_cp", "_one_w", "_one_cp_p", "_one_w_p")

    def __init__(self, rank: int):
        lib = load()
        if lib is None or not hasattr(lib, "am_edit_create"):
            raise NativeUnavailable("native edit session not available")
        self._lib = lib
        # hot-path plumbing: bound function refs + a reusable 1-codepoint
        # buffer with a precomputed ctypes pointer (typing workloads are
        # dominated by single-character splices)
        self._splice_fn = lib.am_edit_splice
        self._len_fn = lib.am_edit_length
        self._one_cp = np.empty(1, np.int32)
        self._one_w = np.ones(1, np.int32)
        self._one_cp_p = _i32(self._one_cp)
        self._one_w_p = _i32(self._one_w)
        self._h = lib.am_edit_create(rank)

    def close(self) -> None:
        if self._h:
            self._lib.am_edit_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def init(self, elem_ids: np.ndarray, winner_ids: np.ndarray, widths: np.ndarray) -> None:
        e = np.ascontiguousarray(elem_ids, np.int64)
        w = np.ascontiguousarray(winner_ids, np.int64)
        wd = np.ascontiguousarray(widths, np.int32)
        self._lib.am_edit_init(self._h, _i64(e), _i64(w), _i32(wd), len(e))

    def length(self) -> int:
        return int(self._len_fn(self._h))

    def op_count(self) -> int:
        return int(self._lib.am_edit_op_count(self._h))

    def splice(self, ctr0: int, pos: int, ndel: int, text: str) -> int:
        """Emit ops for one splice; op ids are ctr0..ctr0+n-1. Returns the
        number of ops emitted; raises on out-of-bounds."""
        nt = len(text)
        if nt == 1:
            cp = ord(text)
            self._one_cp[0] = cp
            if cp > 0x7F:
                from ..types import get_text_encoding

                enc = get_text_encoding()
                self._one_w[0] = (
                    1 + (cp > 0x7F) + (cp > 0x7FF) + (cp > 0xFFFF)
                    if enc == "utf8"
                    else (2 if enc == "utf16" and cp > 0xFFFF else 1)
                )
            else:
                self._one_w[0] = 1
            n = self._splice_fn(self._h, ctr0, pos, ndel, self._one_cp_p, self._one_w_p, 1)
        elif nt == 0:
            n = self._splice_fn(self._h, ctr0, pos, ndel, self._one_cp_p, self._one_w_p, 0)
        else:
            cps = np.frombuffer(text.encode("utf-32-le"), np.uint32).astype(np.int32)
            widths = _cp_widths(cps)
            n = self._splice_fn(self._h, ctr0, pos, ndel, _i32(cps), _i32(widths), nt)
        if n < 0:
            raise _splice_error(int(n))
        return int(n)

    def splice_batch(self, ctr0: int, edits, clamp: bool = True) -> int:
        """Apply many (pos, ndel, text) edits in ONE native call (the
        bulk-ingest path); with ``clamp``, positions and delete counts are
        clamped to the live length per edit. Returns total ops emitted."""
        n = len(edits)
        # vectorized batch prep: the former per-edit python loop cost more
        # than the native splices themselves on full-trace ingests
        pos = np.fromiter((e[0] for e in edits), np.int64, count=n)
        ndel = np.fromiter((e[1] for e in edits), np.int64, count=n)
        texts = [e[2] if len(e) == 3 else ("".join(e[2:]) if len(e) > 3 else "") for e in edits]
        off = np.empty(n + 1, np.int64)
        off[0] = 0
        np.cumsum(
            np.fromiter(map(len, texts), np.int64, count=n), out=off[1:]
        )
        all_text = "".join(texts)
        if all_text:
            cps = np.frombuffer(all_text.encode("utf-32-le"), np.uint32).astype(np.int32)
            widths = _cp_widths(cps)
        else:
            cps = np.zeros(1, np.int32)
            widths = np.ones(1, np.int32)
        r = self._lib.am_edit_splice_batch(
            self._h, ctr0, _i64(pos), _i64(ndel), _i64(off), _i32(cps),
            _i32(widths), n, 1 if clamp else 0,
        )
        if r < 0:
            raise _splice_error(int(r))
        return int(r)

    def export(self, start: int = 0):
        """Emitted ops [start:] in id order: dict of numpy arrays."""
        n = max(self.op_count() - start, 0)
        ids = np.empty(max(n, 1), np.int64)
        refs = np.empty(max(n, 1), np.int64)
        preds = np.empty(max(n, 1), np.int64)
        cps = np.empty(max(n, 1), np.int32)
        widths = np.empty(max(n, 1), np.int32)
        is_del = np.empty(max(n, 1), np.uint8)
        self._lib.am_edit_export(
            self._h, start, _i64(ids), _i64(refs), _i64(preds), _i32(cps),
            _i32(widths), _u8(is_del),
        )
        return {
            "id": ids[:n], "elem_ref": refs[:n], "pred": preds[:n],
            "cp": cps[:n], "width": widths[:n], "is_del": is_del[:n].astype(bool),
        }

    def order(self) -> np.ndarray:
        """Current visible element ids in document order."""
        cap = 1024
        while True:
            out = np.empty(cap, np.int64)
            n = int(self._lib.am_edit_order(self._h, _i64(out), cap))
            if n <= cap:
                return out[:n]
            cap = n


class MapSession:
    """The native map-put session (map_session.cpp): owns one map object's
    visible-winner state inside a transaction; per-op puts resolve pred and
    encode the value payload in C (fastcall map_put entry)."""

    __slots__ = ("_lib", "_h")

    def __init__(self, rank: int):
        lib = load()
        if lib is None or not hasattr(lib, "am_map_create"):
            raise NativeUnavailable("native map session not available")
        self._lib = lib
        self._h = lib.am_map_create(rank)

    def close(self) -> None:
        if self._h:
            self._lib.am_map_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def init(self, keys, winner_ids: np.ndarray) -> None:
        """Preload existing visible keys (utf-8 strings) with winner ids."""
        raws = [k.encode("utf-8") for k in keys]
        offs = np.zeros(len(raws) + 1, np.int64)
        if raws:
            np.cumsum([len(r) for r in raws], out=offs[1:])
        buf = _inbuf(b"".join(raws))
        w = np.ascontiguousarray(winner_ids, np.int64)
        if len(w) == 0:
            w = np.zeros(1, np.int64)
        self._lib.am_map_init(self._h, _u8(buf), _i64(offs), _i64(w), len(raws))

    def op_count(self) -> int:
        return int(self._lib.am_map_op_count(self._h))

    def put(self, ctr: int, key: str, code: int, ival: int = 0,
            fval: float = 0.0, raw: bytes = b"") -> int:
        """ctypes put (tests / non-fastcall paths); the hot path goes
        through fastcall.map_put instead."""
        kb = key.encode("utf-8")
        rb = _inbuf(raw)
        return int(self._lib.am_map_put(
            self._h, ctr, kb, len(kb), code, ival, fval, _u8(rb), len(raw)
        ))

    def export(self, start: int = 0):
        """Emitted ops [start:] in id order: dict of numpy arrays plus the
        raw value payload blob and the interned key table."""
        n_rows = np.zeros(1, np.int64)
        raw_bytes = np.zeros(1, np.int64)
        self._lib.am_map_export_sizes(self._h, start, _i64(n_rows), _i64(raw_bytes))
        n = int(n_rows[0])
        rb = int(raw_bytes[0])
        ids = np.empty(max(n, 1), np.int64)
        key_idx = np.empty(max(n, 1), np.int64)
        preds = np.empty(max(n, 1), np.int64)
        vmeta = np.empty(max(n, 1), np.int64)
        raw = np.empty(max(rb, 1), np.uint8)
        self._lib.am_map_export(
            self._h, start, _i64(ids), _i64(key_idx), _i64(preds),
            _i64(vmeta), _u8(raw),
        )
        nk = np.zeros(1, np.int64)
        kb = np.zeros(1, np.int64)
        self._lib.am_map_keytab_sizes(self._h, _i64(nk), _i64(kb))
        kbytes = np.empty(max(int(kb[0]), 1), np.uint8)
        koffs = np.empty(int(nk[0]) + 1, np.int64)
        self._lib.am_map_keytab(self._h, _u8(kbytes), _i64(koffs))
        blob = kbytes[: int(kb[0])].tobytes()
        keys = [
            blob[int(koffs[i]):int(koffs[i + 1])].decode("utf-8")
            for i in range(int(nk[0]))
        ]
        return {
            "id": ids[:n], "key_idx": key_idx[:n], "pred": preds[:n],
            "vmeta": vmeta[:n], "raw": raw[:rb].tobytes(), "keys": keys,
        }
