"""Process-wide brownout flag.

A deliberately tiny, dependency-free module: the serving layer's
AdmissionController (serve/admission.py) decides *when* the node is in
brownout; the layers that must *react* — storage/durable.py deferring
background compaction, store/docstore.py deferring cold-demotion churn,
rpc.py skipping journal/recency touches on reads — only need a cheap
boolean they can read on hot paths without importing the serving stack
(which would be a circular import: serve imports rpc imports store).

The flag is a ``threading.Event`` so the set/clear transitions are
atomic and ``is_set`` is a single C-level check, safe to call per
request.
"""

from __future__ import annotations

import threading

# set/cleared only by the brownout state machine (AdmissionController)
# and by tests; everyone else reads it via brownout_active()
BROWNOUT = threading.Event()


def brownout_active() -> bool:
    """True while the node is in declared degraded (brownout) mode."""
    return BROWNOUT.is_set()
