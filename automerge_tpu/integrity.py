"""End-to-end integrity: verifiable doc digests, anti-entropy scrub,
and self-healing replicas.

Every robustness layer so far defends against faults that announce
themselves — crashes, partitions, ENOSPC, overload. This module detects
**silent** divergence and heals it, off the ack path and rate-limited:

* **Verifiable doc digests** — a deterministic per-document state
  digest. The accumulator is the XOR of every committed change's 32-byte
  chunk hash, which makes it order-independent by construction (the same
  change set produces the same digest across merge orders, replication
  interleavings, and dense / compressed / run-native residency — the
  digest is a function of history, not representation) and O(1) to
  maintain incrementally: ``DurableDocument`` folds each change's hash
  in as it enters history and only recomputes on open. The exposed
  digest binds the accumulator, the change count, and the sorted heads
  under one SHA-256 (``finalize_digest``), so two documents agree iff
  they hold the same changes *and* the same frontier.
* **Anti-entropy scrubber** (``Scrubber``) — a background loop on every
  serving node. On a replication leader it exchanges digest-at-LSN with
  each follower (compared only when both sides sit at the same stable
  LSN, so live writes can never false-positive); a mismatch counts
  ``cluster.divergence{kind}``, dumps a flight recording, and self-heals
  by resetting the diverged replica from a fresh leader snapshot
  (``replReset`` — a plain catch-up snapshot cannot remove *extra*
  changes, CRDT merge is a union). A replica that re-diverges after a
  repair is quarantined: dropped from the ack-gate quorum
  (``cluster.quarantined`` gauge) rather than silently re-trusted.
* **Device-mirror audit** — sampled spot-checks of the compressed /
  run-native resident image against the dense host oracle
  (``CompressedOpColumns.verify_against``); a mismatch drops the mirror
  for rebuild (``device.mirror_divergence``) instead of serving corrupt
  reads.
* **Durable-tier scrub** — read-back verification of snapshots (strict
  chunk-checksum walk) and journals (the journal's own CRC scan) for
  cold documents and live on-disk files alike, so latent corruption is
  found *before* hydration needs the bytes. A corrupt live doc repairs
  from its own in-memory history (compact = fresh snapshot + truncated
  journal); a corrupt cold doc on a replicated deployment re-fetches
  from a healthy peer (``replHarvest`` union merge) with salvage as the
  last resort for unreplicated docs. Counted as
  ``journal.scrub_corrupt{kind}`` / ``journal.scrub_repaired{kind}``.

Knobs: ``AUTOMERGE_TPU_SCRUB`` (master switch, default on),
``AUTOMERGE_TPU_SCRUB_INTERVAL`` (seconds between rounds, default 15),
``AUTOMERGE_TPU_SCRUB_SAMPLE`` (documents verified per round per
surface, default 8).
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import socket
import threading
from typing import Iterable, List, NamedTuple, Optional

from . import obs
from .utils.leb128 import encode_uleb

DIGEST_VERSION = b"amtpu-digest-v1"

_ZERO32 = bytes(32)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def scrub_enabled() -> bool:
    """Master switch for the background scrubber
    (``AUTOMERGE_TPU_SCRUB=0`` disables — the bench A/B baseline)."""
    return os.environ.get("AUTOMERGE_TPU_SCRUB", "1") != "0"


# -- verifiable doc digests ----------------------------------------------------


class DigestState:
    """Thread-safe incremental digest accumulator over change hashes.

    XOR of 32-byte SHA-256 change hashes: commutative and associative,
    so the accumulator is independent of the order changes entered
    history — exactly the invariance the digest promises across merge
    orders and replication interleavings. ``add`` is O(1) per change
    (32-byte XOR under a lock), cheap enough to ride the ack path's
    change listener.
    """

    __slots__ = ("_lock", "_acc", "_count")

    def __init__(self):
        self._lock = threading.Lock()
        self._acc = _ZERO32
        self._count = 0

    def add(self, change_hash: Optional[bytes]) -> None:
        if not change_hash:
            return
        with self._lock:
            self._acc = bytes(
                a ^ b for a, b in zip(self._acc, change_hash[:32])
            )
            self._count += 1

    def recompute(self, hashes: Iterable[bytes]) -> None:
        """Full rebuild (open / rebuild path): replace the accumulator
        with the XOR over ``hashes``."""
        acc = bytearray(32)
        count = 0
        for h in hashes:
            if not h:
                continue
            for i, b in enumerate(h[:32]):
                acc[i] ^= b
            count += 1
        with self._lock:
            self._acc = bytes(acc)
            self._count = count

    @property
    def count(self) -> int:
        return self._count

    def value(self) -> tuple:
        with self._lock:
            return self._acc, self._count


def finalize_digest(acc: bytes, count: int, heads: Iterable[bytes]) -> str:
    """Bind accumulator + change count + sorted heads into the exposed
    hex digest. Heads are hashed sorted so the frontier's set identity —
    not any discovery order — is what the digest commits to."""
    h = hashlib.sha256()
    h.update(DIGEST_VERSION)
    buf = bytearray()
    encode_uleb(count, buf)
    h.update(bytes(buf))
    h.update(acc)
    for head in sorted(heads):
        h.update(head)
    return h.hexdigest()


def doc_digest(core) -> dict:
    """Full digest of a core ``Document`` from its history — the
    non-incremental path for plain (non-durable) documents and tests."""
    state = DigestState()
    state.recompute(a.stored.hash for a in core.history)
    acc, count = state.value()
    return {
        "digest": finalize_digest(acc, count, core.get_heads()),
        "changes": count,
    }


def column_digests(log, source: str = "dense") -> dict:
    """Per-column SHA-256 over the canonical dense int64 image of an
    ``OpLog``'s resident columns — the column-level oracle the property
    suite diffs on digest mismatch and the device audit's ground truth.

    ``source="dense"`` hashes the host arrays directly;
    ``source="resident"`` decodes the compressed run tables where one
    exists (dense passthrough otherwise), so equality between the two
    maps proves the encoded image faithful.
    """
    import numpy as np

    from .ops import compressed as C

    comp = log.compressed(sync=True) if source == "resident" else None
    out = {}
    n = log.n
    q = len(log.pred_src)
    for name, _mode, _item in C.ROW_SPEC + C.EDGE_SPEC:
        rows = q if name in ("pred_src", "pred_tgt", "pred_key") else n
        arr = getattr(log, name)
        if arr is None:
            continue
        if name in ("insert", "expand"):
            arr = np.asarray(arr, np.bool_).view(np.int8)
        arr = np.asarray(arr[:rows])
        if comp is not None:
            ent = comp.entries.get(name)
            cov = comp.covered.get(name, 0)
            if ent is not None and ent is not C._DENSE and cov == rows:
                arr = ent.decode()
        canon = np.ascontiguousarray(
            np.asarray(arr).astype(np.int64, copy=False))
        h = hashlib.sha256()
        h.update(name.encode("ascii"))
        h.update(canon.tobytes())
        out[name] = h.hexdigest()
    return out


# -- read-back verification (snapshots + journals) -----------------------------


class VerifyReport(NamedTuple):
    """One file's read-back verification result. ``first_bad_offset`` is
    the byte offset of the first frame that failed its checksum (None
    when the file verified clean end to end)."""

    ok: bool
    kind: str  # "snapshot" | "journal"
    total_bytes: int
    valid_bytes: int
    first_bad_offset: Optional[int]
    units: int  # chunks / records verified before the first failure
    reason: str


def verify_snapshot_bytes(data: bytes) -> VerifyReport:
    """Strict sequential chunk walk over snapshot bytes: every chunk
    must parse at the exact expected offset and carry a valid checksum —
    no resynchronisation (``scan_chunks``'s carving tolerance is a
    recovery posture; verification wants the first bad byte).

    Run-coded (ARSN) snapshots verify section-by-section instead: a
    per-section CRC walk plus a chunk-checksum walk over the embedded
    change chunks and a full structural decode, reporting the offset of
    the first bad section (units = sections)."""
    from .storage import runsnap
    from .storage.chunk import parse_chunk

    if runsnap.is_runsnap(data):
        r = runsnap.verify_container(data)
        return VerifyReport(
            r["ok"], "snapshot", r["total_bytes"], r["valid_bytes"],
            r["first_bad_offset"], r["units"], r["reason"] or "",
        )

    pos = 0
    units = 0
    n = len(data)
    while pos < n:
        try:
            chunk, end = parse_chunk(data, pos)
        except Exception as e:  # noqa: BLE001 — any decode fault is a finding
            return VerifyReport(False, "snapshot", n, pos, pos, units,
                                str(e) or type(e).__name__)
        if not chunk.checksum_valid:
            return VerifyReport(False, "snapshot", n, pos, pos, units,
                                "checksum mismatch")
        units += 1
        pos = end
    return VerifyReport(True, "snapshot", n, n, None, units, "")


def verify_journal_bytes(data: bytes) -> VerifyReport:
    """CRC-verify every journal record via the journal's own read-only
    scan. Any stop short of end-of-file — torn tail or mid-file bit rot
    alike — reports the stop offset; the caller decides whether a torn
    tail is expected (crash recovery) or a finding (a cleanly-closed
    cold journal)."""
    from .storage.journal import scan_records

    records, tail = scan_records(data)
    ok = tail.valid_bytes == tail.total_bytes
    return VerifyReport(
        ok, "journal", tail.total_bytes, tail.valid_bytes,
        None if ok else tail.valid_bytes, len(records),
        "" if ok else (tail.reason or "truncated record"),
    )


def verify_doc_dir(path: str, fs=None) -> List[VerifyReport]:
    """Deep read-back scan of one durable document directory (snapshot +
    journal) — the shared core under the durable-tier scrub and
    ``cli.py journal-info --verify``."""
    from .storage.durable import JOURNAL_NAME, SNAPSHOT_NAME
    from .storage.journal import OS_FS

    fs = fs or OS_FS
    out = []
    snap = os.path.join(path, SNAPSHOT_NAME)
    if fs.exists(snap):
        out.append(verify_snapshot_bytes(fs.read_bytes(snap)))
    jpath = os.path.join(path, JOURNAL_NAME)
    if fs.exists(jpath):
        out.append(verify_journal_bytes(fs.read_bytes(jpath)))
    return out


# -- one admin request on a short-lived connection -----------------------------


def _admin_call(addr: str, method: str, params: dict,
                timeout: float = 10.0) -> dict:
    """One synchronous JSON-line request to a peer node. The scrubber
    must not share the replication links' pipelined sockets — a scrub
    probe riding a ship loop's connection would interleave frames."""
    host, _, port = addr.rpartition(":")
    with socket.create_connection((host, int(port)), timeout=timeout) as s:
        s.settimeout(timeout)
        line = json.dumps({"id": 1, "method": method, "params": params})
        s.sendall((line + "\n").encode("utf-8"))
        f = s.makefile("r")
        raw = f.readline()
    if not raw:
        raise OSError(f"no response from {addr}")
    resp = json.loads(raw)
    if "error" in resp:
        err = resp["error"]
        raise RuntimeError(f"{err.get('type')}: {err.get('message')}")
    return resp.get("result") or {}


# -- the scrubber --------------------------------------------------------------


class Scrubber:
    """Background anti-entropy loop for one serving node. All passes are
    sampled (``AUTOMERGE_TPU_SCRUB_SAMPLE`` docs per surface per round,
    round-robin so every doc is eventually covered) and run between the
    ack path's locks, never on it."""

    def __init__(self, rpc, *, interval: Optional[float] = None,
                 sample: Optional[int] = None):
        self.rpc = rpc
        self.interval = (
            interval if interval is not None
            else _env_float("AUTOMERGE_TPU_SCRUB_INTERVAL", 15.0)
        )
        self.sample = (
            sample if sample is not None
            else max(1, _env_int("AUTOMERGE_TPU_SCRUB_SAMPLE", 8))
        )
        # (follower addr, doc name) -> times repaired: the first
        # divergence heals, a re-divergence after repair quarantines
        self._repaired: dict = {}
        self._rr = 0  # round-robin cursor over the doc-name space
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._round_lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None or not scrub_enabled():
            return
        self._thread = threading.Thread(
            target=self._run, name="scrubber", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10)

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.run_round()
            except Exception as e:  # noqa: BLE001 — the loop must not die
                obs.count("scrub.round_error", error=str(e)[:200])

    # -- one round -----------------------------------------------------------

    def run_round(self) -> dict:
        """One full scrub round (also the ``scrubNow`` RPC body, so CI
        can force a deterministic pass instead of sleeping out the
        cadence). Returns a summary of what was checked and found."""
        with self._round_lock:
            summary = {"mirrors": 0, "files": 0, "digests": 0,
                       "corrupt": 0, "divergent": 0, "repaired": 0,
                       "quarantined": 0}
            with obs.span("scrub.round"):
                names = self._sample_names()
                for name in names:
                    summary["mirrors"] += self._audit_mirror(name, summary)
                for name in names:
                    summary["files"] += self._scrub_files(name, summary)
                hub = getattr(self.rpc, "hub", None)
                if hub is not None:
                    self._anti_entropy(hub, summary)
            obs.count("scrub.rounds")
            return summary

    def _sample_names(self) -> List[str]:
        rpc = self.rpc
        with rpc._lock:
            names = set(rpc._durable_names)
        store = getattr(rpc, "store", None)
        if store is not None:
            try:
                names.update(store.names())
            except Exception:  # noqa: BLE001 — store may be mid-shutdown
                pass
        ordered = sorted(names)
        if not ordered:
            return []
        k = min(self.sample, len(ordered))
        start = self._rr % len(ordered)
        self._rr += k
        return [ordered[(start + i) % len(ordered)] for i in range(k)]

    def _live_doc(self, name):
        """The OPEN durable doc for ``name``, or None (never hydrates —
        scrubbing must not churn residency)."""
        rpc = self.rpc
        with rpc._lock:
            h = rpc._durable_names.get(name)
            doc = rpc._docs.get(h) if h is not None else None
        if doc is None or not hasattr(doc, "journal"):
            return None
        if getattr(doc, "_closed", False):
            return None
        return doc

    # -- device-mirror audit -------------------------------------------------

    def _audit_mirror(self, name: str, summary: dict) -> int:
        doc = self._live_doc(name)
        dev = getattr(doc, "device_doc", None) if doc is not None else None
        if dev is None:
            return 0
        if not doc.lock.acquire(timeout=0.2):
            return 0  # busy doc: skip this round, never stall the ack path
        try:
            with obs.span("scrub.mirror", doc=name):
                bad = dev.audit_columns()
        except Exception as e:  # noqa: BLE001 — an audit fault is a finding
            bad = [f"audit-error:{e}"[:80]]
        finally:
            doc.lock.release()
        if not bad:
            return 1
        # the clean-degrade contract: never serve from a mirror the
        # oracle disputes — drop it for rebuild and say so loudly
        for col in bad:
            obs.count("device.mirror_divergence", labels={"column": col})
        obs.event("device.mirror_divergence", doc=name, columns=bad)
        self._flight_dump("mirror_divergence")
        summary["divergent"] += 1
        store = getattr(self.rpc, "store", None)
        try:
            if store is not None and store.tier(name) == "hot":
                store.demote(name, "warm", reason="integrity")
            else:
                doc.drop_device_mirror()
        except Exception:  # noqa: BLE001 — direct drop as fallback
            doc.drop_device_mirror()
        return 1

    # -- durable-tier scrub --------------------------------------------------

    def _scrub_files(self, name: str, summary: dict) -> int:
        doc = self._live_doc(name)
        if doc is not None:
            return self._scrub_live(name, doc, summary)
        store = getattr(self.rpc, "store", None)
        if store is not None and store.tier(name) == "cold":
            return self._scrub_cold(name, summary)
        return 0

    def _doc_fs(self, name: str):
        from .storage.journal import OS_FS

        return getattr(self.rpc, "_chaos_fs", {}).get(name) or OS_FS

    def _scrub_live(self, name: str, doc, summary: dict) -> int:
        """Read-back verify a LIVE doc's on-disk files. Holding the doc
        lock excludes appends and compactions, and a forced fsync first
        flushes buffered tail bytes — so any short CRC prefix is real
        damage, not an in-flight write."""
        from .storage.durable import JOURNAL_NAME, SNAPSHOT_NAME

        if not doc.lock.acquire(timeout=0.2):
            return 0
        try:
            j = doc.journal
            if j.closed or j.poisoned:
                return 0  # degraded docs have their own recovery surface
            fs = self._doc_fs(name)
            with obs.span("scrub.durable", doc=name, tier="live"):
                try:
                    j.sync()
                except Exception:  # noqa: BLE001 — fsync fault, not bit rot
                    return 0
                reports = []
                jpath = os.path.join(doc.path, JOURNAL_NAME)
                if fs.exists(jpath):
                    reports.append(verify_journal_bytes(fs.read_bytes(jpath)))
                spath = os.path.join(doc.path, SNAPSHOT_NAME)
                if fs.exists(spath):
                    reports.append(verify_snapshot_bytes(fs.read_bytes(spath)))
            bad = [r for r in reports if not r.ok]
            if not bad:
                obs.count("journal.scrub_clean")
                return 1
            for r in bad:
                obs.count("journal.scrub_corrupt", labels={"kind": r.kind})
                obs.event("journal.scrub_corrupt", doc=name, kind=r.kind,
                          offset=r.first_bad_offset, reason=r.reason[:120])
            self._flight_dump("scrub_corrupt")
            summary["corrupt"] += len(bad)
            # a live doc's in-memory history is complete (every acked
            # change entered it before the ack) — a fresh snapshot +
            # truncated journal rewrites clean bytes with zero loss
            if doc.compact():
                obs.count("journal.scrub_repaired", labels={"kind": "live"})
                summary["repaired"] += 1
            return 1
        finally:
            doc.lock.release()

    def _scrub_cold(self, name: str, summary: dict) -> int:
        rpc = self.rpc
        try:
            path = rpc._durable_path(name)
        except Exception:  # noqa: BLE001 — not durable mode
            return 0
        fs = self._doc_fs(name)
        with obs.span("scrub.durable", doc=name, tier="cold"):
            try:
                reports = verify_doc_dir(path, fs=fs)
            except Exception as e:  # noqa: BLE001 — unreadable IS corrupt
                reports = [VerifyReport(False, "journal", 0, 0, 0, 0,
                                        str(e)[:120])]
        bad = [r for r in reports if not r.ok]
        if not bad:
            obs.count("journal.scrub_clean")
            return 1
        for r in bad:
            obs.count("journal.scrub_corrupt", labels={"kind": r.kind})
            obs.event("journal.scrub_corrupt", doc=name, kind=r.kind,
                      offset=r.first_bad_offset, reason=r.reason[:120])
        self._flight_dump("scrub_corrupt")
        summary["corrupt"] += len(bad)
        self._repair_cold(name, summary)
        return 1

    def _repair_cold(self, name: str, summary: dict) -> None:
        """Re-fetch a corrupt cold doc from a healthy peer and rewrite
        clean files: salvage-open locally (torn tails truncate, damaged
        snapshot chunks drop), union-merge the peer's full state (every
        change the local damage lost comes back — CRDT merge by hash),
        then compact. Without a peer the salvage alone is the last
        resort, loudly counted."""
        rpc = self.rpc
        store = getattr(rpc, "store", None)
        if store is None:
            return
        peer = self._peer_snapshot(name)
        try:
            doc = store.ensure_open(name)
        except Exception as e:  # noqa: BLE001 — hydration may be bounded
            obs.count("journal.scrub_repair_error", error=str(e)[:200])
            return
        try:
            if peer is not None:
                with doc.lock, doc.ack_scope():
                    doc.load_incremental(peer, on_partial="salvage")
            doc.compact()
        except Exception as e:  # noqa: BLE001
            obs.count("journal.scrub_repair_error", error=str(e)[:200])
            return
        kind = "peer" if peer is not None else "salvage"
        obs.count("journal.scrub_repaired", labels={"kind": kind})
        summary["repaired"] += 1

    def _peer_snapshot(self, name: str) -> Optional[bytes]:
        """Full document state from a healthy replica: the leader asks
        its (non-quarantined) followers, a follower asks its leader.
        None on an unreplicated deployment."""
        rpc = self.rpc
        addrs: List[str] = []
        hub = getattr(rpc, "hub", None)
        if hub is not None:
            addrs = hub.follower_addrs()
        elif getattr(rpc, "leader_hint", None):
            addrs = [rpc.leader_hint]
        for addr in addrs:
            try:
                res = _admin_call(addr, "replHarvest", {"name": name})
                return base64.b64decode(res["snapshot"])
            except Exception as e:  # noqa: BLE001 — try the next peer
                obs.count("scrub.peer_error", error=str(e)[:200])
        return None

    # -- anti-entropy (leader <-> follower digest exchange) ------------------

    def _anti_entropy(self, hub, summary: dict) -> None:
        names = hub.doc_names()
        if not names:
            return
        names = sorted(names)
        k = min(self.sample, len(names))
        start = self._rr % len(names)
        picked = [names[(start + i) % len(names)] for i in range(k)]
        addrs = hub.follower_addrs()
        for name in picked:
            doc = self._live_doc(name)
            if doc is None:
                continue
            lsn_a = hub.lsn(name)
            try:
                mine = doc.doc_digest()
            except Exception:  # noqa: BLE001 — racing close/demote
                continue
            if hub.lsn(name) != lsn_a:
                obs.count("scrub.digest_skipped", labels={"reason": "busy"})
                continue
            for addr in addrs:
                self._compare_follower(hub, addr, name, lsn_a, mine, summary)

    def _compare_follower(self, hub, addr: str, name: str, lsn: int,
                          mine: dict, summary: dict) -> None:
        try:
            theirs = _admin_call(addr, "docDigest", {"name": name},
                                 timeout=hub.io_timeout)
        except Exception as e:  # noqa: BLE001 — link faults aren't rot
            obs.count("scrub.peer_error", error=str(e)[:200])
            return
        if (theirs.get("stream") != hub.stream_id
                or theirs.get("lsn") != lsn):
            obs.count("scrub.digest_skipped", labels={"reason": "lagging"})
            return
        summary["digests"] += 1
        if theirs.get("digest") == mine["digest"]:
            obs.count("cluster.digest_ok")
            return
        # same stream, same LSN, different state: genuine divergence
        summary["divergent"] += 1
        obs.count("cluster.divergence", labels={"kind": "follower_digest"})
        obs.event("cluster.divergence", follower=addr, doc=name, lsn=lsn,
                  leader=mine["digest"], follower_digest=theirs.get("digest"))
        self._flight_dump("divergence")
        key = (addr, name)
        if key in self._repaired:
            # repaired once already and diverged again: the replica is
            # not trustworthy — out of the ack quorum, loudly
            hub.quarantine(addr)
            summary["quarantined"] += 1
            return
        self._repaired[key] = 1
        if self._repair_follower(hub, addr, name):
            summary["repaired"] += 1

    def _repair_follower(self, hub, addr: str, name: str) -> bool:
        """Reset the diverged replica from a fresh leader snapshot. A
        forced catch-up snapshot is NOT enough: CRDT merge is a union,
        so a replica holding *extra* (corrupt or foreign) changes would
        keep them — ``replReset`` wipes the replica's doc state first."""
        from .cluster.replication import encode_cursor

        try:
            data, lsn = hub.snapshot(name)
            cursor = encode_cursor(hub.stream_id, lsn)
            _admin_call(addr, "replReset", {
                "name": name,
                "stream": hub.stream_id,
                "lsn": lsn,
                "snapshot": base64.b64encode(data).decode("ascii"),
                "cursor": base64.b64encode(cursor).decode("ascii"),
            }, timeout=max(hub.io_timeout, 30.0))
        except Exception as e:  # noqa: BLE001
            obs.count("scrub.repair_error", error=str(e)[:200])
            return False
        obs.count("cluster.divergence_repaired")
        return True

    @staticmethod
    def _flight_dump(reason: str) -> None:
        try:
            obs.flight.dump(reason=reason)
        except Exception:  # noqa: BLE001 — diagnostics must not fail scrub
            pass
