"""Core CRDT types: actor ids, op ids, object types, actions, scalar values.

Semantics mirror the reference's type layer (reference:
rust/automerge/src/types.rs) — Lamport-ordered OpIds, action indices 0-7 with
stable storage encoding, SHA-256 change hashes — but the representation is
designed for columnar/device use: OpIds are plain (counter, actor-index) int
pairs so whole op logs pack into int32/int64 device arrays.
"""

from __future__ import annotations

import uuid
from enum import IntEnum
from typing import NamedTuple, Optional, Tuple

# ---------------------------------------------------------------------------
# Actor ids


class ActorId:
    """An actor identity: arbitrary bytes, 16-byte uuid4 by default.

    Reference: types.rs ActorId (random uuid default, hex display).
    """

    __slots__ = ("bytes",)

    def __init__(self, raw: bytes | None = None):
        if raw is None:
            raw = uuid.uuid4().bytes
        if not isinstance(raw, (bytes, bytearray)):
            raise TypeError("ActorId expects bytes")
        self.bytes = bytes(raw)

    @classmethod
    def from_hex(cls, s: str) -> "ActorId":
        return cls(bytes.fromhex(s))

    def to_hex(self) -> str:
        return self.bytes.hex()

    def with_concurrency_suffix(self, level: int) -> "ActorId":
        """Derive the actor id used for isolated (scoped) transactions.

        Mirrors the reference's actor suffixing that avoids opid collisions
        when editing at historical heads (types.rs CONCURRENCY_MAGIC_BYTES).
        """
        suffix = bytearray(_CONCURRENCY_MAGIC)
        n = level
        while True:
            suffix.append(n & 0xFF)
            n >>= 8
            if not n:
                break
        return ActorId(self.bytes + bytes(suffix))

    def __eq__(self, other):
        return isinstance(other, ActorId) and self.bytes == other.bytes

    def __lt__(self, other):
        return self.bytes < other.bytes

    def __le__(self, other):
        return self.bytes <= other.bytes

    def __hash__(self):
        return hash(self.bytes)

    def __repr__(self):
        return f"ActorId({self.bytes.hex()})"


_CONCURRENCY_MAGIC = bytes([0x12, 0x36, 0x34, 0x42])


# ---------------------------------------------------------------------------
# Op ids

# An OpId is (counter, actor_index). actor_index points into a document's
# interned actor table; Lamport order compares (counter, actor-bytes), so
# comparisons that cross actors must go through the actor rank table.
OpId = Tuple[int, int]

ROOT: OpId = (0, 0)  # the root object id sentinel

# Packed op-id layout shared by the device log, bulk rebuild, storage fast
# paths, and the native edit session (session.cpp hard-codes the same 20):
# id = counter << ACTOR_BITS | actor index/rank. Counters < 2^43.
ACTOR_BITS = 20

HEAD: OpId = (0, 0)  # list HEAD element sentinel (counter 0 never collides)


def is_root(obj: OpId) -> bool:
    return obj[0] == 0


def is_head(elem: OpId) -> bool:
    return elem[0] == 0


# ---------------------------------------------------------------------------
# Object types and actions


class ObjType(IntEnum):
    MAP = 0
    LIST = 1
    TEXT = 2
    TABLE = 3

    @property
    def is_sequence(self) -> bool:
        return self in (ObjType.LIST, ObjType.TEXT)


class Action(IntEnum):
    """Stable storage action indices (reference: types.rs action_index)."""

    MAKE_MAP = 0
    PUT = 1
    MAKE_LIST = 2
    DELETE = 3
    MAKE_TEXT = 4
    INCREMENT = 5
    MAKE_TABLE = 6
    MARK = 7  # both mark-begin and mark-end


_MAKE_ACTIONS = {
    Action.MAKE_MAP: ObjType.MAP,
    Action.MAKE_LIST: ObjType.LIST,
    Action.MAKE_TEXT: ObjType.TEXT,
    Action.MAKE_TABLE: ObjType.TABLE,
}

_OBJ_ACTIONS = {v: k for k, v in _MAKE_ACTIONS.items()}


def action_for_objtype(t: ObjType) -> Action:
    return _OBJ_ACTIONS[t]


def objtype_for_action(a: int) -> Optional[ObjType]:
    return _MAKE_ACTIONS.get(Action(a)) if a in (0, 2, 4, 6) else None


def is_make_action(a: int) -> bool:
    return a in (0, 2, 4, 6)


# ---------------------------------------------------------------------------
# Text width encoding
#
# The unit a text index counts in. The reference fixes this per BUILD —
# chars natively, UTF-16 code units under wasm, UTF-8 bytes behind the
# utf8-indexing feature (reference: text_value.rs:5-15, types.rs:701-706
# Op::width). Here the unit is a DOCUMENT property: Document/AutoDoc take
# ``text_encoding`` (constructor + load option) and push it onto a context
# stack around every width-sensitive operation, so documents with
# different encodings coexist in one process. The process-level setting
# remains the default for documents that don't choose one; it must then be
# set before documents are built (changing it under an existing document
# desynchronizes cached width aggregates).

import contextvars as _contextvars

TEXT_ENCODINGS = ("unicode", "utf8", "utf16")
_text_encoding = "unicode"
# innermost active per-document encoding; a ContextVar so threads (the C
# ABI embedding releases the GIL) and async tasks cannot corrupt each
# other's width math
_active_enc: _contextvars.ContextVar = _contextvars.ContextVar(
    "automerge_tpu_text_encoding", default=None
)


def set_text_encoding(encoding: str) -> None:
    """Select the process-default text index unit: "unicode" code points
    (default), "utf8" bytes, or "utf16" code units."""
    global _text_encoding
    if encoding not in TEXT_ENCODINGS:
        raise ValueError(f"unknown text encoding {encoding!r}")
    _text_encoding = encoding


def get_text_encoding() -> str:
    """The ACTIVE text index unit: the innermost document context if one
    is active, else the process default."""
    return _active_enc.get() or _text_encoding


class using_text_encoding:
    """Context manager activating ``encoding`` for the dynamic extent of a
    document operation; ``None`` is a no-op (follow the process default).
    Re-entrant and cheap — the per-document plumbing in core/document.py
    wraps every width-sensitive entry point with this."""

    __slots__ = ("_enc", "_token")

    def __init__(self, encoding):
        if encoding is not None and encoding not in TEXT_ENCODINGS:
            raise ValueError(f"unknown text encoding {encoding!r}")
        self._enc = encoding
        self._token = None

    def __enter__(self):
        if self._enc is not None:
            self._token = _active_enc.set(self._enc)
        return self

    def __exit__(self, *exc):
        if self._token is not None:
            _active_enc.reset(self._token)
            self._token = None
        return False


def str_width(s: str) -> int:
    """Width of ``s`` in the active text index unit."""
    enc = _active_enc.get() or _text_encoding
    if enc == "unicode":
        return len(s)
    if enc == "utf8":
        return len(s.encode("utf-8"))
    return sum(2 if ord(c) > 0xFFFF else 1 for c in s)


# ---------------------------------------------------------------------------
# Scalar values


class ScalarValue(NamedTuple):
    """A tagged scalar. ``tag`` selects the storage value-metadata type code.

    Tags: null, bool, uint, int, f64, str, bytes, counter, timestamp, unknown.
    For ``counter`` the payload is the start value; accumulated increments are
    op-store state, not part of the encoded value. For ``unknown`` the payload
    is (type_code, bytes) — unknown-typed values roundtrip losslessly
    (reference: value.rs ScalarValue::Unknown).
    """

    tag: str
    value: object = None

    @classmethod
    def null(cls):
        return cls("null")

    @classmethod
    def from_py(cls, v) -> "ScalarValue":
        """Best-effort conversion from a plain Python value."""
        if v is None:
            return cls("null")
        if isinstance(v, ScalarValue):
            return v
        if isinstance(v, bool):
            return cls("bool", v)
        if isinstance(v, int):
            return cls("int", v)
        if isinstance(v, float):
            return cls("f64", v)
        if isinstance(v, str):
            return cls("str", v)
        if isinstance(v, (bytes, bytearray)):
            return cls("bytes", bytes(v))
        raise TypeError(f"cannot convert {type(v).__name__} to ScalarValue")

    def to_py(self):
        return None if self.tag == "null" else self.value


# Value metadata type codes (reference: value.rs ValueType)
VALUE_TYPE_NULL = 0
VALUE_TYPE_FALSE = 1
VALUE_TYPE_TRUE = 2
VALUE_TYPE_ULEB = 3
VALUE_TYPE_LEB = 4
VALUE_TYPE_FLOAT = 5
VALUE_TYPE_STRING = 6
VALUE_TYPE_BYTES = 7
VALUE_TYPE_COUNTER = 8
VALUE_TYPE_TIMESTAMP = 9


# ---------------------------------------------------------------------------
# Change hashes

ChangeHash = bytes  # 32-byte SHA-256 digest


def hash_hex(h: ChangeHash) -> str:
    return h.hex()


# ---------------------------------------------------------------------------
# Keys

# A key is either a map property (interned string) or a list element id.
# At the storage boundary props are strings; inside the core they are interned
# indices into the document's prop cache (reference: types.rs Key, interned as
# Key::Map(usize)).


class Key(NamedTuple):
    """Storage-level key: exactly one of ``prop`` / ``elem`` is set."""

    prop: Optional[str] = None
    elem: Optional[OpId] = None

    @classmethod
    def map(cls, prop: str) -> "Key":
        return cls(prop=prop)

    @classmethod
    def seq(cls, elem: OpId) -> "Key":
        return cls(elem=elem)

    @classmethod
    def head(cls) -> "Key":
        return cls(elem=HEAD)
