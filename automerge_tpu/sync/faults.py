"""Fault-injecting transport harness: deterministic lossy channels and a
driver that runs two peers to convergence over them.

The sync layer is verified against hostile transports the way storage
engines are verified against hostile workloads: a seeded ``FaultyChannel``
drops, duplicates, reorders, truncates, and bit-flips frames per a
configurable schedule, and ``SyncDriver`` ticks two ``SyncSession`` peers
(sync/session.py) through it until their documents converge or a tick
budget runs out. Everything is deterministic per seed, so a failing
schedule is a reproducible test case.

    ch_ab = FaultyChannel(seed=7, drop=0.2, dup=0.1, reorder=0.2)
    ch_ba = FaultyChannel(seed=8, drop=0.2, dup=0.1, reorder=0.2)
    stats = SyncDriver(doc_a, doc_b, ch_ab, ch_ba).run()
    assert stats.converged

Channels are tick-clocked: ``send`` stamps each delivery with an arrival
tick (reordering = a random extra delay), ``drain(now)`` returns — in
stamped order — everything due by ``now``.
"""

from __future__ import annotations

import random
from typing import Iterable, List, Optional

from .session import SessionConfig, SyncSession

# explicit per-message schedule entries (fall back to rates when exhausted)
FAULT_KINDS = ("ok", "drop", "dup", "reorder", "truncate", "bitflip")


class ChannelStats:
    __slots__ = ("sent", "delivered", "dropped", "duplicated", "reordered",
                 "truncated", "bitflipped")

    def __init__(self):
        self.sent = 0
        self.delivered = 0
        self.dropped = 0
        self.duplicated = 0
        self.reordered = 0
        self.truncated = 0
        self.bitflipped = 0

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


class Channel:
    """A reliable in-order transport: what protocol.py silently assumes."""

    def __init__(self):
        self._queue: List[tuple[int, int, bytes]] = []  # (due, seq, data)
        self._seq = 0
        self.stats = ChannelStats()

    def send(self, data: bytes, now: int = 0) -> None:
        self.stats.sent += 1
        self._enqueue(data, now)

    def drain(self, now: int) -> List[bytes]:
        """All messages due by ``now``, in (arrival, send-order) order."""
        due = [m for m in self._queue if m[0] <= now]
        self._queue = [m for m in self._queue if m[0] > now]
        due.sort(key=lambda m: (m[0], m[1]))
        self.stats.delivered += len(due)
        return [m[2] for m in due]

    @property
    def pending(self) -> int:
        return len(self._queue)

    def _enqueue(self, data: bytes, now: int, delay: int = 0) -> None:
        self._queue.append((now + delay, self._seq, data))
        self._seq += 1


class FaultyChannel(Channel):
    """A seeded, deterministic lossy transport.

    ``drop``/``dup``/``reorder``/``truncate``/``bitflip`` are independent
    per-message probabilities; ``reorder`` holds a message back 1..
    ``max_delay`` ticks so later sends overtake it. An explicit
    ``schedule`` (sequence of FAULT_KINDS entries, applied by send index)
    overrides the dice for the messages it covers — handy for scripting
    exact adversarial scenarios.
    """

    def __init__(
        self,
        seed: int = 0,
        drop: float = 0.0,
        dup: float = 0.0,
        reorder: float = 0.0,
        truncate: float = 0.0,
        bitflip: float = 0.0,
        max_delay: int = 3,
        schedule: Optional[Iterable[str]] = None,
    ):
        super().__init__()
        self.rng = random.Random(seed)
        self.drop = drop
        self.dup = dup
        self.reorder = reorder
        self.truncate = truncate
        self.bitflip = bitflip
        self.max_delay = max(1, max_delay)
        self.schedule = list(schedule) if schedule is not None else []
        self._sent_index = 0

    def send(self, data: bytes, now: int = 0) -> None:
        self.stats.sent += 1
        idx = self._sent_index
        self._sent_index += 1

        if idx < len(self.schedule):
            kind = self.schedule[idx]
            if kind not in FAULT_KINDS:
                raise ValueError(f"unknown fault kind {kind!r}")
            self._apply(kind, data, now)
            return

        # independent dice per fault class, in severity order
        if self.rng.random() < self.drop:
            self._apply("drop", data, now)
            return
        if self.truncate and self.rng.random() < self.truncate:
            data = self._truncated(data)
        if self.bitflip and self.rng.random() < self.bitflip:
            data = self._bitflipped(data)
        delay = 0
        if self.rng.random() < self.reorder:
            delay = self.rng.randint(1, self.max_delay)
            self.stats.reordered += 1
        self._enqueue(data, now, delay)
        if self.rng.random() < self.dup:
            self.stats.duplicated += 1
            self._enqueue(data, now, self.rng.randint(0, self.max_delay))

    def _apply(self, kind: str, data: bytes, now: int) -> None:
        if kind == "drop":
            self.stats.dropped += 1
            return
        if kind == "dup":
            self.stats.duplicated += 1
            self._enqueue(data, now)
            self._enqueue(data, now)
            return
        if kind == "reorder":
            self.stats.reordered += 1
            self._enqueue(data, now, self.rng.randint(1, self.max_delay))
            return
        if kind == "truncate":
            self._enqueue(self._truncated(data), now)
            return
        if kind == "bitflip":
            self._enqueue(self._bitflipped(data), now)
            return
        self._enqueue(data, now)  # "ok"

    def _truncated(self, data: bytes) -> bytes:
        self.stats.truncated += 1
        if len(data) <= 1:
            return b""
        return data[: self.rng.randrange(1, len(data))]

    def _bitflipped(self, data: bytes) -> bytes:
        self.stats.bitflipped += 1
        if not data:
            return data
        i = self.rng.randrange(len(data))
        out = bytearray(data)
        out[i] ^= 1 << self.rng.randrange(8)
        return bytes(out)


class DriverStats:
    __slots__ = ("converged", "ticks", "a", "b", "channel_ab", "channel_ba")

    def __init__(self, converged, ticks, a, b, channel_ab, channel_ba):
        self.converged = converged
        self.ticks = ticks
        self.a = a  # session_a.stats
        self.b = b
        self.channel_ab = channel_ab
        self.channel_ba = channel_ba

    def __repr__(self):
        return (
            f"DriverStats(converged={self.converged}, ticks={self.ticks}, "
            f"a={self.a}, b={self.b})"
        )


class SyncDriver:
    """Tick two peers over a channel pair until their heads agree.

    Each tick: both sessions poll (possibly emitting a frame), then both
    drain their inbound channel. Convergence = identical heads, both
    sessions idle, both channels empty. Works with any ``Channel``
    subclass; with two plain ``Channel``s it reduces to protocol.sync().
    """

    def __init__(
        self,
        doc_a,
        doc_b,
        channel_ab: Optional[Channel] = None,
        channel_ba: Optional[Channel] = None,
        session_a: Optional[SyncSession] = None,
        session_b: Optional[SyncSession] = None,
        config: Optional[SessionConfig] = None,
    ):
        self.channel_ab = channel_ab if channel_ab is not None else Channel()
        self.channel_ba = channel_ba if channel_ba is not None else Channel()
        cfg = config or SessionConfig()
        self.session_a = session_a or SyncSession(doc_a, config=cfg, epoch=1)
        self.session_b = session_b or SyncSession(doc_b, config=cfg, epoch=2)

    def run(self, max_ticks: int = 2000) -> DriverStats:
        a, b = self.session_a, self.session_b
        ab, ba = self.channel_ab, self.channel_ba
        tick = 0
        for tick in range(1, max_ticks + 1):
            out_a = a.poll(tick)
            if out_a is not None:
                ab.send(out_a, tick)
            out_b = b.poll(tick)
            if out_b is not None:
                ba.send(out_b, tick)
            for data in ab.drain(tick):
                b.receive(data, tick)
            for data in ba.drain(tick):
                a.receive(data, tick)
            if self._settled():
                break
        return DriverStats(
            converged=self._settled(),
            ticks=tick,
            a=a.stats,
            b=b.stats,
            channel_ab=ab.stats,
            channel_ba=ba.stats,
        )

    def _settled(self) -> bool:
        a, b = self.session_a, self.session_b
        return (
            a._doc.get_heads() == b._doc.get_heads()
            and a.converged()
            and b.converged()
            and self.channel_ab.pending == 0
            and self.channel_ba.pending == 0
        )
