"""Resilient sync sessions: the Bloom protocol hardened for lossy transports.

``SyncState``/``generate_sync_message``/``receive_sync_message`` (protocol.py)
assume a perfectly reliable, in-order channel — a single dropped message
deadlocks both peers, a duplicated one wastes a round, and a peer that
loses its state mid-sync (only ``shared_heads`` is persisted, reference:
sync/state.rs) silently stalls. ``SyncSession`` wraps the protocol with the
classic ARQ toolbox:

* **Framing with integrity**: every message travels in a session frame
  ``0x45 | crc32 | flags | ULEB(epoch) | inner`` so arbitrary corruption
  (truncation, bit-flips) is detected at the frame layer and treated as
  loss, never as protocol input.
* **Idempotent receive**: duplicate frames are recognised by digest and
  answered with a retransmission of our own last frame (the duplicate
  usually means our reply was lost).
* **Retry with capped exponential backoff + jitter**: an unanswered frame
  is retransmitted after a timeout that doubles per retry up to a cap,
  with deterministic seeded jitter to avoid lock-step peers.
* **Epoch/reset handshake**: each session instance carries an epoch; a
  frame with an unexpected epoch means the peer restarted (rebuilt its
  state from the persisted ``shared_heads``-only encoding) — we drop our
  per-peer bookkeeping and renegotiate. A RESET flag forces the same from
  the other side.
* **Divergence detector**: when ``stall_rounds`` consecutive received
  messages produce no progress while heads differ (Bloom false positives,
  or a peer whose ``sent_hashes`` suppress resending a change the
  transport destroyed), the session clears
  ``shared_heads``/``sent_hashes`` and forces a full resync on both ends.

All recovery paths emit labeled ``obs`` counters (``sync.retry``,
``sync.reset{source=peer|epoch}``, ``sync.resync``, ``sync.dup``,
``sync.malformed{stage=frame|message}``, ``sync.rejected``,
``sync.device_feed_error``), and the round phases run inside
``obs.span``s (``sync.generate``, ``sync.receive`` > ``sync.apply``) so
a whole session renders as a flame chart via ``obs.export_trace``.

A session may carry a resident ``DeviceDoc`` (``device_doc=``): changes
received off the wire feed its incremental append/re-resolve path
(ops/device_doc.apply_changes), so a device-resident replica tracks the
host document at O(delta) per round instead of rebuilding from the full
change history.

The session is transport- and clock-agnostic: ``poll(now)`` may return
frame bytes to put on the wire, ``receive(data)`` feeds bytes taken off
it. ``now`` is any monotonic number — integer ticks in the fault harness
(sync/faults.py), ``time.monotonic()`` seconds in the RPC frontend.
"""

from __future__ import annotations

import contextlib
import hashlib
import random
import zlib
from collections import OrderedDict
from typing import Optional

from .. import obs
from ..utils.leb128 import decode_uleb, encode_uleb
from .protocol import (
    Message,
    SyncError,
    SyncState,
    generate_sync_message,
    receive_sync_message,
)

SESSION_FRAME_TYPE = 0x45
FLAG_RESET = 0x01

_SEEN_LIMIT = 256  # digests remembered for duplicate detection


def _is_durability_error(e: Exception) -> bool:
    """True for failures of the durable write path (journal I/O), which
    must never be absorbed as protocol-level rejections."""
    if isinstance(e, OSError):
        return True
    try:
        from ..storage.journal import JournalError
    except Exception:  # storage layer absent: nothing to classify
        return False
    return isinstance(e, JournalError)


class SessionConfig:
    """Tuning knobs for one session; all time values are in ``now`` units."""

    __slots__ = (
        "timeout", "backoff_factor", "max_timeout", "jitter",
        "stall_rounds", "seed",
    )

    def __init__(
        self,
        timeout: float = 4.0,
        backoff_factor: float = 2.0,
        max_timeout: float = 64.0,
        jitter: float = 0.25,
        stall_rounds: int = 12,
        seed: int = 0,
    ):
        self.timeout = timeout
        self.backoff_factor = backoff_factor
        self.max_timeout = max_timeout
        self.jitter = jitter
        self.stall_rounds = stall_rounds
        self.seed = seed


def encode_frame(epoch: int, inner: bytes, flags: int = 0, seq: int = 0) -> bytes:
    """``0x45 | crc32(payload) | payload``, payload = flags|epoch|seq|inner.

    ``seq`` is a per-session send counter: it makes every freshly
    generated frame byte-unique, so the receiver's duplicate detector
    only ever fires on true transport duplicates and retransmissions.
    """
    payload = bytearray([flags & 0xFF])
    encode_uleb(epoch, payload)
    encode_uleb(seq, payload)
    payload += inner
    crc = zlib.crc32(bytes(payload)) & 0xFFFFFFFF
    return bytes([SESSION_FRAME_TYPE]) + crc.to_bytes(4, "big") + bytes(payload)


def decode_frame(data: bytes) -> tuple[int, int, int, bytes]:
    """Return (epoch, flags, seq, inner); raise SyncError on any corruption."""
    if not data or data[0] != SESSION_FRAME_TYPE:
        raise SyncError(
            f"expected session frame type 0x45, got {data[:1].hex() or 'EOF'}"
        )
    if len(data) < 6:
        raise SyncError("truncated session frame header")
    crc = int.from_bytes(data[1:5], "big")
    payload = data[5:]
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise SyncError("session frame CRC mismatch")
    flags = payload[0]
    try:
        epoch, pos = decode_uleb(payload, 1)
        seq, pos = decode_uleb(payload, pos)
    except Exception as e:
        raise SyncError(f"truncated session frame header fields: {e}") from e
    return epoch, flags, seq, bytes(payload[pos:])


class SyncSession:
    """One resilient sync conversation with one peer over a lossy channel."""

    def __init__(
        self,
        doc,
        state: Optional[SyncState] = None,
        *,
        config: Optional[SessionConfig] = None,
        epoch: int = 1,
        device_doc=None,
        persist=None,
    ):
        # accept an AutoDoc (auto-commits) or a core Document; the outer
        # object is kept as-is so a durable wrapper's ack_scope (batched
        # journal fsync per received message) is reachable
        self._outer = doc
        self._autodoc = doc if hasattr(doc, "doc") else None
        self._doc = doc.doc if self._autodoc is not None else doc
        # optional resident DeviceDoc: received changes feed its
        # incremental append/re-resolve path directly (O(delta) instead of
        # a from-scratch device rebuild per sync round)
        self.device_doc = device_doc
        self.state = state or SyncState()
        self.config = config or SessionConfig()
        self.epoch = epoch
        self.peer_epoch: Optional[int] = None
        self.stats = {
            "sent": 0, "received": 0, "retries": 0, "resets": 0,
            "resyncs": 0, "dups": 0, "malformed": 0, "rejected": 0,
        }
        self._rng = random.Random(self.config.seed ^ (epoch * 0x9E3779B1))
        self._last_frame: Optional[bytes] = None
        self._last_sent_at: Optional[float] = None
        self._cur_timeout = self.config.timeout
        self._retries = 0
        self._want_retransmit = False
        self._awaiting = False
        self._send_reset = False
        self._noprogress = 0
        self._seq = 0
        self._seen: OrderedDict = OrderedDict()
        # optional persistence hook: called with self.encode() whenever
        # shared_heads change, so a durable peer (storage/durable.py
        # attach_sync_session) survives a restart with its sync progress.
        # Persistence failure must never break the live session.
        self.persist = persist
        self._persisted_shared: Optional[tuple] = None
        # non-None while receive_many() is draining a run of frames: the
        # per-message device feed is deferred into this list and flushed
        # as ONE DeviceDoc.apply_batches call at the end of the run
        self._device_batches: Optional[list] = None

    # -- public surface -----------------------------------------------------

    def poll(self, now: float = 0.0) -> Optional[bytes]:
        """Advance the session clock; return frame bytes to send, or None.

        Call repeatedly — on a timer, after every ``receive``, or once per
        tick of a driving loop. A fresh protocol message always wins;
        otherwise an unanswered frame is retransmitted once its (backed
        off, jittered) timeout expires; a detected stall forces a resync.
        """
        if self._autodoc is not None:
            self._autodoc.commit()
        # progress-free chatter (e.g. our changes frame was lost but our
        # sent_hashes still suppress a resend, so we answer requests with
        # empty change lists forever) → renegotiate from scratch
        if self._noprogress >= self.config.stall_rounds and not self.converged():
            return self._force_resync(now)
        with obs.span("sync.generate"):
            msg = generate_sync_message(self._doc, self.state)
        if msg is not None:
            return self._send(msg, now)

        if self.converged():
            if self._want_retransmit:
                # the peer keeps talking although we are done: its view of
                # our heads is stale — answer with a fresh announcement
                self._want_retransmit = False
                return self._send_ack(now)
            return None

        # not converged and nothing new to generate: we are necessarily
        # awaiting a reply (generate only returns None mid-flight here),
        # so the ARQ timers drive recovery
        if self._awaiting and self._last_frame is not None:
            # duplicate seen → our reply was probably lost: retransmit now
            if self._want_retransmit:
                self._want_retransmit = False
                return self._retransmit(now)
            # unanswered frame past its deadline → retransmit with backoff
            if (
                self._last_sent_at is not None
                and now - self._last_sent_at >= self._cur_timeout
            ):
                return self._retransmit(now)
        return None

    def receive(self, data: bytes, now: float = 0.0) -> bool:
        """Feed bytes off the wire. Returns True if they advanced the
        session, False if they were dropped (corrupt or duplicate).
        Never raises on untrusted input."""
        with obs.span("sync.receive", bytes=len(data)):
            return self._receive(data, now)

    def receive_many(self, frames, now: float = 0.0, device_feed=None) -> list:
        """Drain a run of pending wire frames in arrival order, coalescing
        the resident-device feed: instead of one ``DeviceDoc.apply_changes``
        per message, every message's changes collect into a single
        ``apply_batches`` call at the end — on accelerator backends that
        pipelines the kernel launches (h2d staging of batch k+1 overlaps
        batch k's kernel), amortizing per-launch cost across the run.

        ``device_feed`` (a callable taking the collected batches)
        replaces the direct ``apply_batches`` call — the serving layer
        passes its cross-document batcher here so concurrently-draining
        sessions share ONE kernel launch (ops/batched.py).

        Host-document semantics are identical to calling ``receive`` per
        frame; returns the per-frame accepted flags."""
        accepted = []
        # a single frame keeps the plain per-message path — unless an
        # external device_feed is attached (the cross-doc batcher): then
        # even one frame's changes defer so they can join other docs'
        # concurrently-draining feeds in a shared launch
        if self.device_doc is None or (len(frames) <= 1 and device_feed is None):
            for data in frames:
                accepted.append(self.receive(data, now))
            return accepted
        self._device_batches = batches = []
        try:
            for data in frames:
                accepted.append(self.receive(data, now))
        finally:
            self._device_batches = None
        if batches:
            obs.count("sync.coalesced_batches", n=len(batches))
            try:
                if device_feed is not None:
                    device_feed(batches)
                else:
                    self.device_doc.apply_batches(batches)
            except Exception as e:  # noqa: BLE001 — isolate the sidecar
                obs.count("sync.device_feed_error", error=str(e)[:200])
        return accepted

    def _receive(self, data: bytes, now: float) -> bool:
        try:
            epoch, flags, _seq, inner = decode_frame(data)
        except Exception as e:
            # tolerate a bare protocol message for interop with plain
            # SyncState peers (no envelope, no resilience semantics)
            try:
                msg = Message.decode(data)
            except Exception:
                self.stats["malformed"] += 1
                obs.count("sync.malformed", labels={"stage": "frame"},
                          error=str(e))
                return False
            return self._apply(msg, now)

        digest = hashlib.sha256(data).digest()[:16]
        if digest in self._seen:
            self.stats["dups"] += 1
            obs.count("sync.dup")
            self._want_retransmit = True
            return False
        self._seen[digest] = None
        while len(self._seen) > _SEEN_LIMIT:
            self._seen.popitem(last=False)

        if self.peer_epoch is None:
            self.peer_epoch = epoch
        elif epoch != self.peer_epoch:
            # peer restarted: its state is rebuilt from shared_heads only
            self._on_peer_reset(epoch)
        if flags & FLAG_RESET:
            self._hard_reset(keep_shared=False)
            self.stats["resets"] += 1
            obs.count("sync.reset", labels={"source": "peer"})

        if not inner:
            return True  # pure control frame (reset/ack)
        try:
            msg = Message.decode(inner)
        except Exception as e:
            self.stats["malformed"] += 1
            obs.count("sync.malformed", labels={"stage": "message"},
                      error=str(e))
            return False
        return self._apply(msg, now)

    def converged(self) -> bool:
        """True once the peer's last reported heads equal ours."""
        their = self.state.their_heads
        return their is not None and set(their) == set(self._doc.get_heads())

    def encode(self) -> bytes:
        """Persist across restarts (shared_heads only, like SyncState)."""
        return self.state.encode()

    @classmethod
    def restore(cls, doc, data: bytes, *, epoch: int, config=None) -> "SyncSession":
        """Rebuild a session after a restart. ``epoch`` MUST differ from
        the pre-restart session's epoch so the peer notices and drops its
        stale bookkeeping."""
        return cls(doc, SyncState.decode(data), config=config, epoch=epoch)

    # -- internals ----------------------------------------------------------

    def _send(self, msg: Message, now: float) -> bytes:
        flags = FLAG_RESET if self._send_reset else 0
        self._send_reset = False
        frame = encode_frame(self.epoch, msg.encode(), flags, self._next_seq())
        self._last_frame = frame
        self._last_sent_at = now
        self._cur_timeout = self._with_jitter(self.config.timeout)
        self._retries = 0
        self._awaiting = True
        self.stats["sent"] += 1
        return frame

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _send_ack(self, now: float) -> bytes:
        """A fresh heads announcement for a peer whose view of us is stale.
        Not part of the ARQ window: we expect no reply to it."""
        msg = Message(
            heads=self._doc.get_heads(), need=[], have=[], changes=[]
        )
        self.stats["sent"] += 1
        return encode_frame(self.epoch, msg.encode(), 0, self._next_seq())

    def _retransmit(self, now: float) -> bytes:
        self._last_sent_at = now
        self._retries += 1
        self.stats["retries"] += 1
        self._cur_timeout = self._with_jitter(
            min(
                self.config.timeout * self.config.backoff_factor ** self._retries,
                self.config.max_timeout,
            )
        )
        obs.count("sync.retry", attempt=self._retries)
        return self._last_frame

    def _with_jitter(self, timeout: float) -> float:
        return timeout * (1.0 + self.config.jitter * self._rng.random())

    def _apply(self, msg: Message, now: float) -> bool:
        with obs.span("sync.apply", changes=len(msg.changes)):
            return self._apply_inner(msg, now)

    def _apply_inner(self, msg: Message, now: float) -> bool:
        if self._autodoc is not None:
            self._autodoc.commit()
        before = self._doc.get_heads()
        # a durable document batches this message's journal fsyncs into
        # one at the scope exit; the except below stays narrowly around
        # the PROTOCOL apply so observer/journal failures propagate
        # instead of being miscounted as rejected frames
        scope = getattr(self._outer, "ack_scope", None)
        with scope() if scope is not None else contextlib.nullcontext():
            try:
                receive_sync_message(self._doc, self.state, msg)
            except Exception as e:
                # a durable write-path failure (the journal listener fires
                # inside apply_changes) is NOT a rejected frame: the ack
                # guarantee is at stake, so it must propagate
                if _is_durability_error(e):
                    raise
                # a well-framed message whose changes the document rejects
                # (e.g. duplicate (actor, seq) from a peer that lost its
                # doc and re-created divergent history): absorb, count,
                # keep going
                self.stats["rejected"] += 1
                obs.count("sync.rejected", error=str(e))
                return False
            # persist inside the scope: the meta record rides the same
            # single boundary fsync as the message's change records
            self._maybe_persist()
        if self._autodoc is not None:
            self._autodoc._notify_patches()
        if self.device_doc is not None and msg.changes:
            if self._device_batches is not None:
                # inside receive_many: defer into one apply_batches call
                self._device_batches.append(list(msg.changes))
            else:
                # feed the resident device document incrementally; device-
                # side trouble must never break the host sync session
                try:
                    self.device_doc.apply_changes(msg.changes)
                except Exception as e:  # noqa: BLE001 — isolate the sidecar
                    obs.count("sync.device_feed_error", error=str(e)[:200])
        self.stats["received"] += 1
        self._awaiting = False
        self._retries = 0
        self._cur_timeout = self._with_jitter(self.config.timeout)
        progressed = (
            self._doc.get_heads() != before
            or self.converged()
        )
        if progressed:
            self._noprogress = 0
        else:
            self._noprogress += 1
        return True

    def _maybe_persist(self) -> None:
        if self.persist is None:
            return
        cur = tuple(self.state.shared_heads)
        if cur == self._persisted_shared:
            return
        try:
            self.persist(self.encode())
        except Exception as e:  # noqa: BLE001 — persistence is best-effort
            # NOT marked persisted: a transient failure retries on the
            # next call even if shared_heads never change again
            obs.count("sync.persist_error", error=str(e)[:200])
        else:
            self._persisted_shared = cur

    def _on_peer_reset(self, new_epoch: int) -> None:
        self.peer_epoch = new_epoch
        self._hard_reset(keep_shared=True)
        self.stats["resets"] += 1
        obs.count("sync.reset", labels={"source": "epoch"})

    def _hard_reset(self, keep_shared: bool) -> None:
        shared = list(self.state.shared_heads) if keep_shared else []
        st = SyncState()
        st.shared_heads = shared
        self.state = st
        self._last_frame = None
        self._last_sent_at = None
        self._retries = 0
        self._awaiting = False
        self._cur_timeout = self.config.timeout
        self._noprogress = 0
        # a reset that cleared shared_heads must persist that too, or a
        # restart would resurrect heads the resync just disowned
        self._maybe_persist()

    def _force_resync(self, now: float) -> Optional[bytes]:
        """Divergence detected: renegotiate from nothing and tell the peer
        (RESET flag) to drop its suppressing sent_hashes too."""
        self.stats["resyncs"] += 1
        obs.count("sync.resync")
        self._hard_reset(keep_shared=False)
        self._send_reset = True
        msg = generate_sync_message(self._doc, self.state)
        if msg is None:  # nothing to say yet: send a pure control frame
            frame = encode_frame(self.epoch, b"", FLAG_RESET, self._next_seq())
            self._send_reset = False
            self._last_frame = frame
            self._last_sent_at = now
            self._awaiting = True
            self.stats["sent"] += 1
            return frame
        return self._send(msg, now)
