from .bloom import BloomFilter
from .protocol import (
    Have,
    Message,
    SyncError,
    SyncState,
    generate_sync_message,
    receive_sync_message,
    sync,
)

__all__ = [
    "BloomFilter",
    "Have",
    "Message",
    "SyncError",
    "SyncState",
    "generate_sync_message",
    "receive_sync_message",
    "sync",
]
