from .bloom import BloomFilter
from .faults import Channel, FaultyChannel, SyncDriver
from .protocol import (
    Have,
    Message,
    SyncError,
    SyncState,
    generate_sync_message,
    receive_sync_message,
    sync,
)
from .session import SessionConfig, SyncSession

__all__ = [
    "BloomFilter",
    "Channel",
    "FaultyChannel",
    "Have",
    "Message",
    "SessionConfig",
    "SyncDriver",
    "SyncError",
    "SyncState",
    "SyncSession",
    "generate_sync_message",
    "receive_sync_message",
    "sync",
]
