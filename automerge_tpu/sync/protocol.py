"""Peer-to-peer sync protocol: Bloom-filter delta discovery over any
reliable in-order transport.

Wire- and semantics-compatible with the reference (reference:
rust/automerge/src/sync.rs, algorithm from arXiv:2012.00472): each peer
repeatedly sends ``Message {heads, need, have: [{last_sync, bloom}],
changes}``; rounds continue until both sides return None. The sync state
persists per peer; only ``shared_heads`` survives re-encoding across
sessions (reference: sync/state.rs).

Message type byte 0x42, state type byte 0x43 (reference: sync.rs:131,
sync/state.rs:7).
"""

from __future__ import annotations

from typing import List, Optional, Set

from ..storage.change import StoredChange, parse_change
from ..utils.leb128 import decode_uleb, encode_uleb
from .bloom import BloomFilter

MESSAGE_TYPE_SYNC = 0x42
SYNC_STATE_TYPE = 0x43
HASH_SIZE = 32


from ..errors import AutomergeError


class SyncError(AutomergeError):
    pass


class Have:
    """A summary of changes the sender already has (an implicit request for
    everything it does not)."""

    __slots__ = ("last_sync", "bloom")

    def __init__(
        self,
        last_sync: Optional[List[bytes]] = None,
        bloom: Optional[BloomFilter] = None,
    ):
        self.last_sync = last_sync or []
        self.bloom = bloom or BloomFilter()

    def __eq__(self, other):
        return (
            isinstance(other, Have)
            and self.last_sync == other.last_sync
            and self.bloom == other.bloom
        )


class Message:
    __slots__ = ("heads", "need", "have", "changes")

    def __init__(
        self,
        heads: List[bytes],
        need: List[bytes],
        have: List[Have],
        changes: List[StoredChange],
    ):
        self.heads = heads
        self.need = need
        self.have = have
        self.changes = changes

    def encode(self) -> bytes:
        out = bytearray([MESSAGE_TYPE_SYNC])
        _encode_hashes(out, self.heads)
        _encode_hashes(out, self.need)
        encode_uleb(len(self.have), out)
        for h in self.have:
            _encode_hashes(out, h.last_sync)
            bloom = h.bloom.to_bytes()
            encode_uleb(len(bloom), out)
            out += bloom
        encode_uleb(len(self.changes), out)
        for c in self.changes:
            raw = c.raw_bytes
            if raw is None:
                raise SyncError("change missing raw bytes")
            encode_uleb(len(raw), out)
            out += raw
        return bytes(out)

    @classmethod
    def decode(cls, data: bytes) -> "Message":
        """Decode untrusted bytes; every malformed input raises SyncError."""
        if not data or data[0] != MESSAGE_TYPE_SYNC:
            raise SyncError(
                f"expected sync message type 0x42, got {data[:1].hex() or 'EOF'}"
            )
        try:
            return cls._decode_body(data)
        except SyncError:
            raise
        except Exception as e:
            raise SyncError(f"malformed sync message: {e}") from e

    @classmethod
    def _decode_body(cls, data: bytes) -> "Message":
        pos = 1
        heads, pos = _decode_hashes(data, pos)
        need, pos = _decode_hashes(data, pos)
        n, pos = decode_uleb(data, pos)
        have = []
        for _ in range(n):
            last_sync, pos = _decode_hashes(data, pos)
            blen, pos = decode_uleb(data, pos)
            if pos + blen > len(data):
                raise SyncError("bloom filter length overruns message")
            bloom = BloomFilter.from_bytes(data[pos : pos + blen])
            pos += blen
            have.append(Have(last_sync, bloom))
        n, pos = decode_uleb(data, pos)
        changes = []
        for _ in range(n):
            clen, pos = decode_uleb(data, pos)
            if pos + clen > len(data):
                raise SyncError("change length overruns message")
            change, _ = parse_change(data[pos : pos + clen], 0)
            pos += clen
            changes.append(change)
        return cls(heads, need, have, changes)


class SyncState:
    """Per-peer synchronisation state (reference: sync/state.rs State)."""

    def __init__(self):
        self.shared_heads: List[bytes] = []
        self.last_sent_heads: List[bytes] = []
        self.their_heads: Optional[List[bytes]] = None
        self.their_need: Optional[List[bytes]] = None
        self.their_have: Optional[List[Have]] = None
        self.sent_hashes: Set[bytes] = set()
        self.in_flight = False

    def encode(self) -> bytes:
        """Persist across sessions: only shared_heads is reusable."""
        out = bytearray([SYNC_STATE_TYPE])
        _encode_hashes(out, self.shared_heads)
        return bytes(out)

    @classmethod
    def decode(cls, data: bytes) -> "SyncState":
        if not data or data[0] != SYNC_STATE_TYPE:
            raise SyncError(
                f"expected sync state type 0x43, got {data[:1].hex() or 'EOF'}"
            )
        heads, _ = _decode_hashes(data, 1)
        st = cls()
        st.shared_heads = heads
        st.their_have = []
        return st


def _encode_hashes(out: bytearray, hashes: List[bytes]) -> None:
    hashes = sorted(hashes)
    encode_uleb(len(hashes), out)
    for h in hashes:
        out += h


def _decode_hashes(data: bytes, pos: int):
    n, pos = decode_uleb(data, pos)
    out = []
    for _ in range(n):
        if pos + HASH_SIZE > len(data):
            raise SyncError("truncated hash list")
        out.append(bytes(data[pos : pos + HASH_SIZE]))
        pos += HASH_SIZE
    return out, pos


# ---------------------------------------------------------------------------
# protocol driver (reference: sync.rs:134-383)


def generate_sync_message(doc, state: SyncState) -> Optional[Message]:
    """Produce the next message for the peer, or None if nothing to send.

    ``doc`` is a core Document (AutoDoc wraps this with an auto-commit).
    """
    our_heads = doc.get_heads()
    our_need = doc.get_missing_deps(state.their_heads or [])
    their_heads_set = set(state.their_heads or [])

    if all(h in their_heads_set for h in our_need):
        our_have = [_make_bloom(doc, list(state.shared_heads))]
    else:
        our_have = []

    # peer references a last_sync point we do not know: tell it to reset
    if state.their_have:
        first = state.their_have[0]
        if not all(doc.get_change_by_hash(h) is not None for h in first.last_sync):
            return Message(heads=our_heads, need=[], have=[Have()], changes=[])

    if state.their_have is not None and state.their_need is not None:
        changes_to_send = _changes_to_send(doc, state.their_have, state.their_need)
    else:
        changes_to_send = []

    heads_unchanged = state.last_sent_heads == our_heads
    heads_equal = state.their_heads == our_heads
    changes_to_send = [
        c for c in changes_to_send if c.hash not in state.sent_hashes
    ]

    if heads_unchanged:
        if heads_equal and not changes_to_send:
            return None
        if state.in_flight:
            return None

    state.last_sent_heads = list(our_heads)
    state.sent_hashes.update(c.hash for c in changes_to_send)
    state.in_flight = True
    return Message(
        heads=our_heads, need=our_need, have=our_have, changes=changes_to_send
    )


def receive_sync_message(doc, state: SyncState, message: Message) -> None:
    """Apply a received message: absorb changes, advance shared heads."""
    state.in_flight = False
    before_heads = doc.get_heads()

    if message.changes:
        doc.apply_changes(message.changes)
        state.shared_heads = _advance_heads(
            set(before_heads), set(doc.get_heads()), state.shared_heads
        )

    # trim sent hashes to those the peer has definitely not seen
    known_msg_heads = [
        h for h in message.heads if doc.get_change_by_hash(h) is not None
    ]
    doc.change_graph.remove_ancestors(state.sent_hashes, known_msg_heads)

    if not message.changes and message.heads == before_heads:
        state.last_sent_heads = list(message.heads)

    if len(known_msg_heads) == len(message.heads):
        state.shared_heads = list(message.heads)
        # peer lost all its data: reset for a full resync
        if not message.heads:
            state.last_sent_heads = []
            state.sent_hashes = set()
    else:
        state.shared_heads = sorted(
            set(state.shared_heads) | set(known_msg_heads)
        )

    state.their_have = message.have
    state.their_heads = message.heads
    state.their_need = message.need


def sync(doc_a, doc_b, state_a=None, state_b=None, max_rounds: int = 100):
    """Drive two in-process documents to convergence (test/CLI helper)."""
    state_a = state_a or SyncState()
    state_b = state_b or SyncState()
    for _ in range(max_rounds):
        msg_a = generate_sync_message(doc_a, state_a)
        if msg_a is not None:
            receive_sync_message(doc_b, state_b, Message.decode(msg_a.encode()))
        msg_b = generate_sync_message(doc_b, state_b)
        if msg_b is not None:
            receive_sync_message(doc_a, state_a, Message.decode(msg_b.encode()))
        if msg_a is None and msg_b is None:
            return state_a, state_b
    raise SyncError(f"no convergence after {max_rounds} rounds")


def _make_bloom(doc, last_sync: List[bytes]) -> Have:
    new_changes = doc.get_changes(last_sync)
    return Have(
        last_sync=last_sync,
        bloom=BloomFilter.from_hashes(c.hash for c in new_changes),
    )


def _changes_to_send(doc, have: List[Have], need: List[bytes]) -> List[StoredChange]:
    if not have:
        out = []
        for h in need:
            c = doc.get_change_by_hash(h)
            if c is not None:
                out.append(c)
        return out

    last_sync_hashes: Set[bytes] = set()
    blooms = []
    for h in have:
        last_sync_hashes.update(h.last_sync)
        blooms.append(h.bloom)

    changes = doc.get_changes(sorted(last_sync_hashes))

    dependents = {}
    to_send: Set[bytes] = set()
    for c in changes:
        for dep in c.dependencies:
            dependents.setdefault(dep, []).append(c.hash)
        if all(not b.contains(c.hash) for b in blooms):
            to_send.add(c.hash)

    # everything that transitively depends on a bloom-negative change must
    # also be sent (its deps would otherwise be unresolvable)
    stack = list(to_send)
    while stack:
        h = stack.pop()
        for dep in dependents.get(h, ()):
            if dep not in to_send:
                to_send.add(dep)
                stack.append(dep)

    out = []
    for h in need:
        if h not in to_send:
            c = doc.get_change_by_hash(h)
            if c is not None:
                out.append(c)
    for c in changes:
        if c.hash in to_send:
            out.append(c)
    return out


def _advance_heads(
    old_heads: Set[bytes], new_heads: Set[bytes], old_shared: List[bytes]
) -> List[bytes]:
    advanced = {h for h in new_heads if h not in old_heads}
    advanced.update(h for h in old_shared if h in new_heads)
    return sorted(advanced)
