"""Bloom filter for sync-protocol change-set summaries.

Wire- and probe-compatible with the reference (reference:
rust/automerge/src/sync/bloom.rs): 10 bits/entry, 7 probes (~1% false
positives), probes derived by triple hashing from the change hash itself —
the hash is already a SHA-256 digest, so its first twelve bytes serve as
three independent 32-bit hash values. Parameters are carried in the wire
format, so they can change without breaking the protocol.
"""

from __future__ import annotations

import math
from typing import Iterable, List

from ..utils.leb128 import decode_uleb, encode_uleb

BITS_PER_ENTRY = 10
NUM_PROBES = 7


class BloomFilter:
    __slots__ = ("num_entries", "num_bits_per_entry", "num_probes", "bits")

    def __init__(
        self,
        num_entries: int = 0,
        num_bits_per_entry: int = BITS_PER_ENTRY,
        num_probes: int = NUM_PROBES,
        bits: bytes = b"",
    ):
        self.num_entries = num_entries
        self.num_bits_per_entry = num_bits_per_entry
        self.num_probes = num_probes
        self.bits = bytearray(bits)

    @classmethod
    def from_hashes(cls, hashes: Iterable[bytes]) -> "BloomFilter":
        hashes = list(hashes)
        f = cls(num_entries=len(hashes))
        f.bits = bytearray(_bits_capacity(len(hashes), f.num_bits_per_entry))
        for h in hashes:
            f._add_hash(h)
        return f

    # -- probes ------------------------------------------------------------

    def _probes(self, h: bytes) -> List[int]:
        modulo = 8 * len(self.bits)
        x = int.from_bytes(h[0:4], "little") % modulo
        y = int.from_bytes(h[4:8], "little") % modulo
        z = int.from_bytes(h[8:12], "little") % modulo
        probes = [x]
        for _ in range(1, self.num_probes):
            x = (x + y) % modulo
            y = (y + z) % modulo
            probes.append(x)
        return probes

    def _add_hash(self, h: bytes) -> None:
        for p in self._probes(h):
            self.bits[p >> 3] |= 1 << (p & 7)

    def contains(self, h: bytes) -> bool:
        if self.num_entries == 0 or not self.bits:
            return False
        return all(self.bits[p >> 3] & (1 << (p & 7)) for p in self._probes(h))

    # -- wire format -------------------------------------------------------

    def to_bytes(self) -> bytes:
        if self.num_entries == 0:
            return b""
        out = bytearray()
        encode_uleb(self.num_entries, out)
        encode_uleb(self.num_bits_per_entry, out)
        encode_uleb(self.num_probes, out)
        out += self.bits
        return bytes(out)

    @classmethod
    def from_bytes(cls, data: bytes) -> "BloomFilter":
        if not data:
            return cls()
        pos = 0
        num_entries, pos = decode_uleb(data, pos)
        bpe, pos = decode_uleb(data, pos)
        probes, pos = decode_uleb(data, pos)
        # untrusted input: reject parameters outside u32 (reference parses
        # with leb128_u32) and cap probes/bits-per-entry so a malicious
        # filter cannot make contains() loop unboundedly
        if num_entries >= 1 << 32 or bpe >= 1 << 32 or probes >= 1 << 32:
            raise ValueError("bloom filter parameter exceeds u32")
        if probes > 1024 or bpe > 1024:
            raise ValueError("unreasonable bloom filter parameters")
        cap = _bits_capacity(num_entries, bpe)
        if len(data) - pos < cap:
            raise ValueError("bloom filter bits truncated")
        return cls(num_entries, bpe, probes, data[pos : pos + cap])

    def __eq__(self, other):
        return (
            isinstance(other, BloomFilter)
            and self.num_entries == other.num_entries
            and self.num_bits_per_entry == other.num_bits_per_entry
            and self.num_probes == other.num_probes
            and self.bits == other.bits
        )


def _bits_capacity(num_entries: int, bits_per_entry: int) -> int:
    return math.ceil(num_entries * bits_per_entry / 8)
