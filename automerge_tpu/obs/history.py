"""History rings: fixed-memory downsampled metric trends.

Every instrument in the registry is a *now* view; flight dumps keep
raw recent deltas but no aligned time base. Soaks and chaos scenes
need to assert on **trends** — "staleness spiked under the delay dial
and recovered" — which requires bounded, time-aligned history. This
module keeps it: per allowlisted metric family, three fixed-size ring
tiers at 1 s / 10 s / 60 s resolution (120 slots each by default, so
two minutes of fine grain, twenty minutes of medium, two hours of
coarse — all in a few KB per series, forever).

Sampling reads one registry snapshot per tick and aggregates **across
label sets** per family: counters record the per-slot *delta* of the
label-summed total (a rate, once divided by the tier interval);
gauges record the per-slot *max* and *last* of the label-max (max is
what spike assertions want; last is what a dashboard line wants).
Downsampling is pure aggregation: a 10 s slot is the sum of deltas /
max of maxes / last of lasts over its ten 1 s slots, so counter
totals stay additive and gauge envelopes stay true across tiers.

Bounded by construction: the allowlist is explicit, the series count
is capped (``cap``; families past it are counted in
``dropped_series``), and every tier is a fixed-``maxlen`` deque — no
input can grow the ring.

Surfaces: the ``historyStatus`` RPC (rpc.py), the ``cluster-history``
CLI (cli.py), and every flight dump (the recorder's history provider
hook), so a post-mortem sees the trend that led to the dump.

Env knobs: ``AUTOMERGE_TPU_HISTORY=0`` keeps the serving layer from
starting the background sampler; ``AUTOMERGE_TPU_HISTORY_METRICS``
replaces the default allowlist (comma-separated family names);
``AUTOMERGE_TPU_HISTORY_SLOTS`` resizes the per-tier ring (default
120). Tests drive ``sample(now=...)`` manually for determinism.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

import automerge_tpu.obs as _obs

# (interval seconds, slots-of-previous-tier per slot); tier 0 is the
# base sampling interval, each later tier downsamples the one before
TIERS = (1.0, 10.0, 60.0)

DEFAULT_ALLOWLIST = (
    "cluster.staleness_seconds",
    "cluster.replication_lag",
    "serve.load_score",
    "rpc.bytes_in",
    "cluster.records_shipped",
)


def _allowlist_from_env() -> Tuple[str, ...]:
    raw = os.environ.get("AUTOMERGE_TPU_HISTORY_METRICS")
    if raw is None:
        return DEFAULT_ALLOWLIST
    return tuple(s.strip() for s in raw.split(",") if s.strip())


class _Series:
    """One family's three ring tiers plus the counter baseline."""

    __slots__ = ("name", "type", "tiers", "prev_total", "pending")

    def __init__(self, name: str, type_: str, slots: int):
        self.name = name
        self.type = type_  # "counter" | "gauge"
        self.tiers: List[deque] = [deque(maxlen=slots) for _ in TIERS]
        self.prev_total: Optional[float] = None
        # per-tier accumulator for the slot being built from the tier
        # below: [n_slots, delta_sum, max, last, t_start]
        self.pending: List[Optional[list]] = [None for _ in TIERS[1:]]


class HistoryRing:
    """Fixed-memory downsampling recorder over a metric allowlist."""

    def __init__(
        self,
        allowlist: Optional[Tuple[str, ...]] = None,
        slots: Optional[int] = None,
        cap: int = 64,
        registry=None,
    ):
        self.allowlist = tuple(
            allowlist if allowlist is not None else _allowlist_from_env())
        if slots is None:
            try:
                slots = int(os.environ.get(
                    "AUTOMERGE_TPU_HISTORY_SLOTS", "120"))
            except ValueError:
                slots = 120
        self.slots = max(2, int(slots))
        self.cap = max(1, int(cap))
        self.registry = registry if registry is not None else _obs.registry
        self._series: Dict[Tuple[str, str], _Series] = {}
        self._lock = threading.Lock()
        self.samples = 0
        self.dropped_series = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- sampling ------------------------------------------------------------

    def sample(self, now: Optional[float] = None) -> int:
        """Take one tier-0 sample from the registry; returns the number
        of series updated. Callers own the cadence (the background
        sampler ticks every ``TIERS[0]`` seconds; tests call directly
        with an explicit ``now``)."""
        if now is None:
            now = _obs.now()
        want = set(self.allowlist)
        # label-aggregated totals per (name, type): counters sum,
        # gauges take (max, last) across label sets
        agg: Dict[Tuple[str, str], list] = {}
        for e in self.registry.snapshot():
            if e["name"] not in want or e["type"] not in ("counter", "gauge"):
                continue
            key = (e["name"], e["type"])
            v = float(e["value"])
            slot = agg.get(key)
            if slot is None:
                agg[key] = [v, v, v]  # [sum, max, last]
            else:
                slot[0] += v
                slot[1] = max(slot[1], v)
                slot[2] = v
        n = 0
        with self._lock:
            self.samples += 1
            for key, (vsum, vmax, vlast) in sorted(agg.items()):
                s = self._series.get(key)
                if s is None:
                    if len(self._series) >= self.cap:
                        self.dropped_series += 1
                        continue
                    s = _Series(key[0], key[1], self.slots)
                    self._series[key] = s
                self._push_locked(s, now, vsum, vmax, vlast)
                n += 1
        return n

    def _push_locked(self, s: _Series, now: float, vsum: float,
                     vmax: float, vlast: float) -> None:
        if s.type == "counter":
            prev = s.prev_total if s.prev_total is not None else vsum
            delta = max(0.0, vsum - prev)  # reset-protected
            s.prev_total = vsum
            slot = {"t": now, "delta": delta}
        else:
            slot = {"t": now, "max": vmax, "last": vlast}
        s.tiers[0].append(slot)
        self._downsample_locked(s, 1, slot)

    def _downsample_locked(self, s: _Series, tier: int, slot: dict) -> None:
        """Fold one completed slot of ``tier-1`` into ``tier``'s pending
        accumulator; emit (and recurse) when the accumulator covers a
        full coarse interval."""
        if tier >= len(TIERS):
            return
        per = int(round(TIERS[tier] / TIERS[tier - 1]))
        acc = s.pending[tier - 1]
        if acc is None:
            acc = s.pending[tier - 1] = [
                0, 0.0, float("-inf"), 0.0, slot["t"]]
        acc[0] += 1
        if s.type == "counter":
            acc[1] += slot["delta"]
        else:
            acc[2] = max(acc[2], slot["max"])
            acc[3] = slot["last"]
        if acc[0] < per:
            return
        if s.type == "counter":
            coarse = {"t": acc[4], "delta": acc[1]}
        else:
            coarse = {"t": acc[4], "max": acc[2], "last": acc[3]}
        s.tiers[tier].append(coarse)
        s.pending[tier - 1] = None
        self._downsample_locked(s, tier + 1, coarse)

    # -- background sampler --------------------------------------------------

    def start(self) -> bool:
        """Start the 1 Hz daemon sampler (idempotent)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return False
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="obs-history", daemon=True)
            self._thread.start()
            return True

    def _run(self) -> None:
        while not self._stop.wait(TIERS[0]):
            try:
                self.sample()
            except Exception:  # noqa: BLE001 — telemetry never kills serving
                pass

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    # -- reading -------------------------------------------------------------

    def series(self, name: str, tier: int = 0,
               type_: Optional[str] = None) -> List[dict]:
        """One family's slots at one tier, oldest first."""
        with self._lock:
            for (n, t), s in self._series.items():
                if n == name and (type_ is None or t == type_):
                    return list(s.tiers[tier])
        return []

    def status(self, name: Optional[str] = None,
               tier: Optional[int] = None) -> dict:
        """Queryable dump: every series' rings (optionally filtered to
        one family / one tier)."""
        tiers = [
            {"intervalSeconds": iv, "slots": self.slots}
            for iv in TIERS
        ]
        out_series = []
        with self._lock:
            for (n, t), s in sorted(self._series.items()):
                if name is not None and n != name:
                    continue
                rings = {}
                for i in range(len(TIERS)):
                    if tier is not None and i != tier:
                        continue
                    rings[str(i)] = list(s.tiers[i])
                out_series.append({
                    "name": n, "type": t, "tiers": rings,
                })
            return {
                "allowlist": list(self.allowlist),
                "tiers": tiers,
                "cap": self.cap,
                "samples": self.samples,
                "droppedSeries": self.dropped_series,
                "series": out_series,
            }

    def reset(self) -> None:
        with self._lock:
            self._series.clear()
            self.samples = 0
            self.dropped_series = 0


# -- process-global ring (what the serving layer starts) ----------------------

ring = HistoryRing()


def enabled() -> bool:
    return os.environ.get("AUTOMERGE_TPU_HISTORY", "1") != "0"


def start() -> bool:
    """Start the global sampler when enabled; installs the flight-dump
    provider so every dump carries the trend that led to it."""
    if not enabled():
        return False
    _obs.flight.history_provider = ring.status
    return ring.start()


def status(name: Optional[str] = None, tier: Optional[int] = None) -> dict:
    return ring.status(name=name, tier=tier)


def reset() -> None:
    ring.reset()
