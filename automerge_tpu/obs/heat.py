"""Bounded per-document heat accounting: who is hot, and how.

The registry's per-doc gauges say how *big* a document is
(``doc.journal_bytes``) and when it was last touched
(``doc.last_access_seconds``); nothing says how *often* it is touched,
or what it costs to serve. This module keeps that signal: a bounded
top-K table of per-document decayed rates — read / write / sync
request counts, request bytes, and attributed drain seconds (fed from
the cycle profiler's per-doc cost attribution) — that the placement
advisor (cluster/advisor.py) and ``perf-report`` rank against.

Mechanics: one table entry per document, each kind's score a
half-life-decayed accumulator (``score *= 2**(-dt/half_life)`` on
touch, default half-life 60 s). At steady state a constant event rate
``r`` holds the score at ``r * half_life / ln 2``, so the exported
per-second rate is ``score * ln2 / half_life``. The table is
**space-saving** bounded: at capacity a new document evicts the
minimum-ranked entry and *inherits its rank score* (plus an ``err``
field recording the inherited overestimate) — the classic top-K
guarantee that a genuinely hot document can never be kept out by a
stream of cold ones, at the price of a bounded overestimate.

Rank is the decayed read+write+sync request score only: bytes and
drain seconds ride along for the advisor but do not decide eviction
(their units would drown the request counts).

Surfaces: ``doc.heat{doc,kind}`` gauges for the top-N
(``publish_gauges``; previously-published series for documents that
fell out of the top set are removed — same hygiene contract as
``obs.remove_doc_gauges``), the ``heatStatus`` RPC (rpc.py), a ranked
section in ``perf-report`` (obs/prof.py), and the advisor snapshot.

Env knobs: ``AUTOMERGE_TPU_HEAT=0`` disables accounting entirely (the
disabled ``note`` is one attribute check — run_obs holds it to the
standard overhead budget); ``AUTOMERGE_TPU_HEAT_DOCS`` caps the table
(default 256); ``AUTOMERGE_TPU_HEAT_HALFLIFE`` sets the decay
half-life in seconds (default 60).

Every public method takes an optional explicit ``now`` (monotonic
seconds) so tests drive decay deterministically; production callers
omit it and get ``obs.now()``.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Dict, List, Optional

import automerge_tpu.obs as _obs

KINDS = ("read", "write", "sync", "bytes", "drain_s")

# kinds whose decayed score contributes to the eviction/ranking order
_RANK_KINDS = ("read", "write", "sync")

_LN2 = math.log(2.0)


def _env_pos(name: str, default: float) -> float:
    try:
        v = float(os.environ.get(name, ""))
    except ValueError:
        return default
    return v if v > 0 else default


class _Entry:
    __slots__ = ("scores", "totals", "stamp", "err")

    def __init__(self, now: float, err: float = 0.0):
        self.scores: Dict[str, float] = {}
        self.totals: Dict[str, float] = {}
        self.stamp = now
        self.err = err  # rank score inherited from an evicted entry

    def decay_to(self, now: float, half_life: float) -> None:
        dt = now - self.stamp
        if dt <= 0.0:
            return
        f = 2.0 ** (-dt / half_life)
        for k in self.scores:
            self.scores[k] *= f
        self.err *= f
        self.stamp = now

    def rank(self) -> float:
        s = self.err
        for k in _RANK_KINDS:
            s += self.scores.get(k, 0.0)
        return s


class HeatTable:
    """Bounded space-saving table of per-document decayed heat."""

    def __init__(
        self,
        cap: Optional[int] = None,
        half_life: Optional[float] = None,
        enabled: Optional[bool] = None,
    ):
        if enabled is None:
            enabled = os.environ.get("AUTOMERGE_TPU_HEAT", "1") != "0"
        self.enabled = bool(enabled)
        self.cap = int(cap if cap is not None
                       else _env_pos("AUTOMERGE_TPU_HEAT_DOCS", 256))
        self.cap = max(1, self.cap)
        self.half_life = float(
            half_life if half_life is not None
            else _env_pos("AUTOMERGE_TPU_HEAT_HALFLIFE", 60.0))
        self._entries: Dict[str, _Entry] = {}
        self._lock = threading.Lock()
        self._evictions = 0
        # (doc, kind) series currently published as doc.heat gauges
        self._published: set = set()

    # -- recording -----------------------------------------------------------

    def note(self, doc: str, kind: str, n: float = 1.0,
             now: Optional[float] = None) -> None:
        """Record ``n`` units of ``kind`` heat against ``doc``. The
        disabled path returns after one attribute check."""
        if not self.enabled:
            return
        if not doc or kind not in KINDS:
            return
        if now is None:
            now = _obs.now()
        with self._lock:
            e = self._entries.get(doc)
            if e is None:
                e = self._admit_locked(doc, now)
            else:
                e.decay_to(now, self.half_life)
            e.scores[kind] = e.scores.get(kind, 0.0) + n
            e.totals[kind] = e.totals.get(kind, 0.0) + n

    def _admit_locked(self, doc: str, now: float) -> _Entry:
        if len(self._entries) < self.cap:
            e = _Entry(now)
            self._entries[doc] = e
            return e
        # space-saving eviction: drop the minimum-ranked entry; the
        # newcomer inherits its rank so a hot doc arriving late still
        # climbs (err records the overestimate)
        victim, vmin = None, math.inf
        for name, cand in self._entries.items():
            cand.decay_to(now, self.half_life)
            r = cand.rank()
            if r < vmin:
                victim, vmin = name, r
        assert victim is not None
        del self._entries[victim]
        self._evictions += 1
        e = _Entry(now, err=vmin)
        self._entries[doc] = e
        return e

    def forget(self, doc: str) -> bool:
        """Drop one document's entry (close/migrate-out hygiene)."""
        with self._lock:
            return self._entries.pop(doc, None) is not None

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()
            self._evictions = 0
            self._published.clear()

    # -- reading -------------------------------------------------------------

    def rate_of(self, score: float) -> float:
        """Steady-state per-second rate implied by a decayed score."""
        return score * _LN2 / self.half_life

    def snapshot(self, now: Optional[float] = None,
                 top: Optional[int] = None) -> dict:
        """Ranked heat snapshot: ``{"entries": [{doc, rank, rates,
        totals, err}, ...], ...}`` sorted hottest-first (ties broken by
        doc name for determinism)."""
        if now is None:
            now = _obs.now()
        out: List[dict] = []
        with self._lock:
            for doc, e in self._entries.items():
                e.decay_to(now, self.half_life)
                out.append({
                    "doc": doc,
                    "rank": e.rank(),
                    "rates": {k: self.rate_of(v)
                              for k, v in e.scores.items() if v > 0.0},
                    "totals": dict(e.totals),
                    "err": e.err,
                })
            evictions = self._evictions
        out.sort(key=lambda r: (-r["rank"], r["doc"]))
        if top is not None:
            out = out[:top]
        return {
            "enabled": self.enabled,
            "cap": self.cap,
            "halfLifeSeconds": self.half_life,
            "docs": len(self._entries),
            "evictions": evictions,
            "entries": out,
        }

    # -- gauge export --------------------------------------------------------

    def publish_gauges(self, top: int = 16,
                       now: Optional[float] = None) -> int:
        """Export the top-N entries as ``doc.heat{doc,kind}`` gauges
        (per-second rates; ``drain_s`` is seconds-of-work per second,
        i.e. utilization). Series published on a previous call for docs
        that fell out of the top set are removed so the registry's
        cardinality slots keep circulating. Returns the series count."""
        snap = self.snapshot(now=now, top=top)
        fresh = set()
        for e in snap["entries"]:
            for kind, rate in e["rates"].items():
                key = (e["doc"], kind)
                fresh.add(key)
                _obs.gauge_set("doc.heat", rate,
                               labels={"doc": e["doc"], "kind": kind})
        for doc, kind in self._published - fresh:
            _obs.gauge_remove("doc.heat", {"doc": doc, "kind": kind})
        self._published = fresh
        return len(fresh)


# -- process-global table (what the rpc/serve/prof hooks feed) ---------------

table = HeatTable()


def note(doc: str, kind: str, n: float = 1.0,
         now: Optional[float] = None) -> None:
    table.note(doc, kind, n, now=now)


def snapshot(now: Optional[float] = None, top: Optional[int] = None) -> dict:
    return table.snapshot(now=now, top=top)


def reset() -> None:
    """Tests: clear the global table (keeps enabled/cap config)."""
    table.reset()
