"""Hierarchical spans: a contextvar parent chain and a bounded ring buffer
of completed spans, exportable as Chrome-trace/Perfetto JSON.

A span nests under whatever span is active in the same context when it
starts (``contextvars`` — so async tasks and threads each get their own
chain), records wall time on exit, and lands in the ``SpanRecorder`` ring
buffer. The buffer is bounded (``AUTOMERGE_TPU_SPAN_BUFFER`` entries,
default 4096; 0 disables recording) so always-on span collection costs a
deque append, never unbounded memory.

``export_chrome_trace`` writes the buffer in the Chrome trace-event JSON
format (``{"traceEvents": [{"ph": "X", ...}]}``) that
https://ui.perfetto.dev and chrome://tracing open directly: one complete
("X") event per span, nested by time containment per thread, with the
span's fields (and its span/parent ids) under ``args``.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import random
import threading
import uuid
from collections import deque
from time import perf_counter
from typing import List, Optional

# all span timestamps are seconds since this process-wide origin, so the
# exported trace starts near ts=0 regardless of perf_counter's epoch
_ORIGIN = perf_counter()

# span ids start from a process-random base (high bits random, low bits a
# plain counter): parent/link references must stay unambiguous when flight
# dumps from SEVERAL processes are stitched into one timeline, and a
# counter starting at 1 would collide in every process
_ids = itertools.count((random.getrandbits(62) & ~0xFFFFFFFF) | 1)
current_span: contextvars.ContextVar[Optional[int]] = contextvars.ContextVar(
    "automerge_tpu_span", default=None
)
# the active cross-process trace id (None outside any propagated trace —
# the pay-for-what-you-use default: one contextvar read per span exit)
current_trace: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "automerge_tpu_trace", default=None
)


def next_span_id() -> int:
    return next(_ids)


def new_trace_id() -> str:
    """A fresh 16-hex-char trace id for a request entering the system."""
    return uuid.uuid4().hex[:16]


class SpanRecord:
    __slots__ = ("name", "span_id", "parent_id", "start", "duration",
                 "thread_id", "fields", "status", "trace_id", "links")

    def __init__(self, name, span_id, parent_id, start, duration,
                 thread_id, fields, status, trace_id=None, links=None):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = start          # seconds since _ORIGIN
        self.duration = duration    # seconds
        self.thread_id = thread_id
        self.fields = fields
        self.status = status        # "ok" | "error"
        self.trace_id = trace_id    # cross-process trace id, or None
        # links: ((trace_id, span_id), ...) — spans this one covers
        # without parenting them (group commit, batched launches)
        self.links = links

    def to_chrome_event(self, pid: int) -> dict:
        args = {str(k): _arg(v) for k, v in self.fields.items()}
        args["span_id"] = self.span_id
        if self.parent_id is not None:
            args["parent_id"] = self.parent_id
        if self.trace_id is not None:
            args["trace_id"] = self.trace_id
        if self.links:
            args["links"] = [list(l) for l in self.links]
        if self.status != "ok":
            args["status"] = self.status
        return {
            "name": self.name,
            "cat": "automerge_tpu",
            "ph": "X",
            "ts": round(self.start * 1e6, 3),
            "dur": round(self.duration * 1e6, 3),
            "pid": pid,
            "tid": self.thread_id,
            "args": args,
        }

    def to_dict(self) -> dict:
        """JSON form for flight-recorder dumps (obs/flight.py)."""
        d = {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start,
            "duration": self.duration,
            "thread_id": self.thread_id,
            "fields": {str(k): _arg(v) for k, v in self.fields.items()},
            "status": self.status,
        }
        if self.trace_id is not None:
            d["trace_id"] = self.trace_id
        if self.links:
            d["links"] = [list(l) for l in self.links]
        return d


def _arg(v):
    if isinstance(v, (int, float, bool, str)) or v is None:
        return v
    return str(v)


class SpanRecorder:
    """Bounded ring of completed SpanRecords, newest-wins."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=max(capacity, 0))

    def record(self, rec: SpanRecord) -> bool:
        """Append; returns True when the ring was full and an old span
        was silently evicted (the caller counts ``obs.spans_dropped``)."""
        if self.capacity <= 0:
            return False
        with self._lock:
            evicted = len(self._buf) == self._buf.maxlen
            self._buf.append(rec)
        return evicted

    def snapshot(self) -> List[SpanRecord]:
        with self._lock:
            return list(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def export_chrome_trace(self, path: str) -> int:
        """Write the buffered spans as Chrome-trace JSON; returns the
        number of events written."""
        records = self.snapshot()
        pid = os.getpid()
        events = [r.to_chrome_event(pid) for r in records]
        events.sort(key=lambda e: e["ts"])
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"producer": "automerge_tpu.obs"},
        }
        with open(path, "w") as f:
            json.dump(doc, f)
        return len(events)


def now() -> float:
    """Seconds since the recorder origin (what SpanRecord.start uses)."""
    return perf_counter() - _ORIGIN
