"""Hierarchical spans: a contextvar parent chain and a bounded ring buffer
of completed spans, exportable as Chrome-trace/Perfetto JSON.

A span nests under whatever span is active in the same context when it
starts (``contextvars`` — so async tasks and threads each get their own
chain), records wall time on exit, and lands in the ``SpanRecorder`` ring
buffer. The buffer is bounded (``AUTOMERGE_TPU_SPAN_BUFFER`` entries,
default 4096; 0 disables recording) so always-on span collection costs a
deque append, never unbounded memory.

``export_chrome_trace`` writes the buffer in the Chrome trace-event JSON
format (``{"traceEvents": [{"ph": "X", ...}]}``) that
https://ui.perfetto.dev and chrome://tracing open directly: one complete
("X") event per span, nested by time containment per thread, with the
span's fields (and its span/parent ids) under ``args``.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import threading
from collections import deque
from time import perf_counter
from typing import List, Optional

# all span timestamps are seconds since this process-wide origin, so the
# exported trace starts near ts=0 regardless of perf_counter's epoch
_ORIGIN = perf_counter()

_ids = itertools.count(1)
current_span: contextvars.ContextVar[Optional[int]] = contextvars.ContextVar(
    "automerge_tpu_span", default=None
)


def next_span_id() -> int:
    return next(_ids)


class SpanRecord:
    __slots__ = ("name", "span_id", "parent_id", "start", "duration",
                 "thread_id", "fields", "status")

    def __init__(self, name, span_id, parent_id, start, duration,
                 thread_id, fields, status):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = start          # seconds since _ORIGIN
        self.duration = duration    # seconds
        self.thread_id = thread_id
        self.fields = fields
        self.status = status        # "ok" | "error"

    def to_chrome_event(self, pid: int) -> dict:
        args = {str(k): _arg(v) for k, v in self.fields.items()}
        args["span_id"] = self.span_id
        if self.parent_id is not None:
            args["parent_id"] = self.parent_id
        if self.status != "ok":
            args["status"] = self.status
        return {
            "name": self.name,
            "cat": "automerge_tpu",
            "ph": "X",
            "ts": round(self.start * 1e6, 3),
            "dur": round(self.duration * 1e6, 3),
            "pid": pid,
            "tid": self.thread_id,
            "args": args,
        }


def _arg(v):
    if isinstance(v, (int, float, bool, str)) or v is None:
        return v
    return str(v)


class SpanRecorder:
    """Bounded ring of completed SpanRecords, newest-wins."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=max(capacity, 0))

    def record(self, rec: SpanRecord) -> None:
        if self.capacity <= 0:
            return
        with self._lock:
            self._buf.append(rec)

    def snapshot(self) -> List[SpanRecord]:
        with self._lock:
            return list(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def export_chrome_trace(self, path: str) -> int:
        """Write the buffered spans as Chrome-trace JSON; returns the
        number of events written."""
        records = self.snapshot()
        pid = os.getpid()
        events = [r.to_chrome_event(pid) for r in records]
        events.sort(key=lambda e: e["ts"])
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"producer": "automerge_tpu.obs"},
        }
        with open(path, "w") as f:
            json.dump(doc, f)
        return len(events)


def now() -> float:
    """Seconds since the recorder origin (what SpanRecord.start uses)."""
    return perf_counter() - _ORIGIN
