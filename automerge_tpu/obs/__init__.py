"""Unified observability: labeled metrics + hierarchical spans.

The single entry point the rest of the codebase instruments against
(``trace.py`` keeps its ``count``/``time``/``span``/``event`` names as
thin shims over this module):

* ``obs.count(name, n, labels={...}, **fields)`` — labeled counter; the
  aggregate (label-summed) value also lands in the legacy
  ``trace.counters`` dict so existing consumers keep working.
* ``obs.gauge_set(name, v, labels=...)`` — last-write-wins gauge.
* ``obs.observe(name, v, labels=...)`` — histogram observation.
* ``with obs.span(name, labels=..., **fields):`` — hierarchical timed
  span: nests via a contextvar, accumulates wall time into the legacy
  ``trace.timings`` dict AND a log-bucketed histogram (p50/p95/p99), and
  lands in the bounded ring buffer that ``obs.export_trace(path)`` dumps
  as Chrome-trace/Perfetto JSON.
* ``obs.render_prometheus()`` — text exposition of every instrument
  (scraped via the RPC ``metrics`` method or the CLI ``metrics``
  subcommand).

Env knobs: ``AUTOMERGE_TPU_TRACE=1`` turns on per-event debug log lines
(same as before); ``AUTOMERGE_TPU_SPAN_BUFFER=N`` sizes the span ring
buffer (default 4096, 0 disables span recording while keeping the
timing/histogram accumulation).

Everything here is thread-safe: one registry RLock guards instruments and
the legacy dicts (the RPC server and the device staging path touch them
concurrently).
"""

from __future__ import annotations

import logging
import os
import re
import threading
from time import perf_counter as _perf_counter
from typing import Optional

from .metrics import (  # noqa: F401 — re-exported API
    FACTOR,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    merge_prometheus,
    parse_prometheus,
    sanitize_metric_name,
)
from .spans import (  # noqa: F401 — re-exported API
    _ORIGIN,
    SpanRecord,
    SpanRecorder,
    current_span,
    current_trace,
    new_trace_id,
    next_span_id,
    now,
)
from .flight import FlightRecorder, merge_flights  # noqa: F401

logger = logging.getLogger("automerge_tpu")

if os.environ.get("AUTOMERGE_TPU_TRACE"):
    logger.setLevel(logging.DEBUG)
    if not logger.handlers:
        logging.basicConfig()

_DEBUG = logging.DEBUG


def enabled() -> bool:
    return logger.isEnabledFor(_DEBUG)


# -- globals -----------------------------------------------------------------

registry = MetricsRegistry()

_SPAN_BUFFER = int(os.environ.get("AUTOMERGE_TPU_SPAN_BUFFER", "4096"))
recorder = SpanRecorder(_SPAN_BUFFER)

# the per-process flight recorder (obs/flight.py): bounded rings of
# recent events + metric deltas around the span ring, dumped to disk on
# exit/failover once a server entry point calls ``flight.install``
flight = FlightRecorder(recorder, registry)

# drain-cycle profiler hooks (obs/prof.py installs these when imported;
# None — the default until a server/bench imports prof — costs one
# global read per span). When installed, each hook is a contextvar read
# unless a prof.cycle is actually active in the calling context.
cycle_enter = None
cycle_exit = None

# the legacy back-compat views (trace.counters / trace.timings alias these
# exact dict objects): counters hold the label-aggregated totals; timings
# hold [total_seconds, count] per span name. Mutated only under
# ``registry.lock`` by this module; external consumers (bench stash/
# restore) read and swap contents single-threaded.
legacy_counters: dict = {}
legacy_timings: dict = {}


# -- structured event lines --------------------------------------------------

_NEEDS_QUOTE = re.compile(r'[\s"=\\]')


def _fmt_field(v) -> str:
    """One ``k=v`` value: quoted + escaped when it contains whitespace,
    ``=``, quotes or backslashes, so trace lines stay machine-parseable
    even for error messages."""
    s = str(v)
    if _NEEDS_QUOTE.search(s) or not s:
        s = (
            '"'
            + s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
            + '"'
        )
    return s


def event(name: str, **fields) -> None:
    """One structured trace line: ``name k=v k=v`` (values quoted as
    needed). Always lands in the flight recorder's bounded event ring;
    the debug log line still requires ``AUTOMERGE_TPU_TRACE``."""
    flight.note_event(name, fields)
    if logger.isEnabledFor(_DEBUG):
        body = " ".join(f"{k}={_fmt_field(v)}" for k, v in fields.items())
        logger.debug("%s %s", name, body)


_EVENT_TOKEN = re.compile(r'(\w+)=("(?:[^"\\]|\\.)*"|\S*)')


def parse_event_fields(body: str) -> dict:
    """Inverse of the ``event`` field encoding (for log consumers/tests)."""
    from .metrics import _unescape_label_value

    out = {}
    for m in _EVENT_TOKEN.finditer(body):
        k, v = m.group(1), m.group(2)
        if v.startswith('"') and v.endswith('"') and len(v) >= 2:
            # single-pass unescape: sequential str.replace would decode
            # an escaped backslash-then-n ('\\\\n') as backslash+newline
            v = _unescape_label_value(v[1:-1])
        out[k] = v
    return out


# -- counters / gauges / histograms ------------------------------------------


def count(name: str, n: int = 1, labels: Optional[dict] = None, **fields) -> None:
    """Increment the named (optionally labeled) counter. The aggregate
    across labels also lands in the legacy ``trace.counters`` dict; a
    debug event line is emitted when tracing is on."""
    with registry.lock:
        registry._get_locked(name, "counter", labels or {})._inc_locked(n)
        total = legacy_counters.get(name, 0) + n
        legacy_counters[name] = total
    flight.note_delta("count", name, labels, n)
    if logger.isEnabledFor(_DEBUG):
        event(name, n=n, total=total, **(labels or {}), **fields)


def gauge_set(name: str, value: float, labels: Optional[dict] = None) -> None:
    registry.gauge(name, **(labels or {})).set(value)
    flight.note_delta("gauge", name, labels, value)


def observe(name: str, value: float, labels: Optional[dict] = None) -> None:
    registry.histogram(name, **(labels or {})).observe(value)
    flight.note_delta("observe", name, labels, value)


def remove_labels(name: str, labels: dict, type_: Optional[str] = None) -> int:
    """Remove one label set from the named families (see
    ``MetricsRegistry.remove_labels``) — the hygiene call for
    per-subject series whose subject is gone."""
    return registry.remove_labels(name, labels, type_=type_)


def gauge_remove(name: str, labels: Optional[dict] = None) -> bool:
    return registry.gauge_remove(name, **(labels or {}))


# the per-document gauge families the durable and device layers export;
# one hygiene call drops every series for a document that closed or
# went cold, so the cardinality cap's slots keep circulating among LIVE
# documents instead of filling with dead ones (past the cap, new docs
# would collapse into {overflow=true} — exactly the admission signal
# the tiered store cannot afford to lose)
DOC_GAUGES = ("doc.journal_bytes", "doc.last_access_seconds",
              "doc.digest_changes")
DEVICE_DOC_GAUGES = ("doc.resident_ops", "doc.device_bytes",
                     "doc.compress_ratio")
# per-queue gauges keyed by the serving layer's shard key (the integer
# doc HANDLE, not the durable name) — removed via ``queue_key``
QUEUE_GAUGES = ("rpc.queue_depth",)


def remove_doc_gauges(doc_name: Optional[str], *, device_only: bool = False,
                      queue_key=None) -> int:
    n = 0
    if queue_key is not None:
        for fam in QUEUE_GAUGES:
            n += registry.remove_labels(
                fam, {"doc": str(queue_key)}, type_="gauge")
    if not doc_name:
        return n
    names = DEVICE_DOC_GAUGES if device_only else DOC_GAUGES + DEVICE_DOC_GAUGES
    for fam in names:
        n += registry.remove_labels(fam, {"doc": doc_name}, type_="gauge")
    return n


def reset_counters() -> None:
    """Clear the legacy counter view (the registry's Prometheus counters
    stay monotone over process life, as scrapers expect)."""
    with registry.lock:
        legacy_counters.clear()


def reset_timers() -> None:
    """Clear the legacy timings view (histograms/spans are unaffected)."""
    with registry.lock:
        legacy_timings.clear()


def timing_summary() -> dict:
    """{name: {"s": total seconds, "n": span count}} snapshot of the
    legacy timing accumulators."""
    with registry.lock:
        return {
            k: {"s": round(v[0], 6), "n": v[1]}
            for k, v in legacy_timings.items()
        }


def percentiles(name: str, qs=(0.5, 0.95, 0.99), labels: Optional[dict] = None) -> dict:
    """{q: estimate} from the named histogram (0.0s when empty)."""
    h = registry.histogram(name, **(labels or {}))
    return {q: h.percentile(q) for q in qs}


def counter_values(name: str, label: str) -> dict:
    """{label value: count} across the named counter's label sets (e.g.
    ``counter_values("device.kernel_launches", "path")`` — the per-path
    dispatch totals the bench JSON and the multichip harness export)."""
    return {
        e["labels"].get(label, ""): e["value"]
        for e in snapshot()
        if e["type"] == "counter" and e["name"] == name
    }


# -- hierarchical spans ------------------------------------------------------


class span:
    """``with obs.span("device.kernel", rows=n):`` — a timed span that
    nests under the contextually-active span, accumulates into
    ``trace.timings`` and the ``name`` histogram, and records into the
    ring buffer for Perfetto export. Always on; cost is two clock reads,
    one lock round-trip and a deque append.

    ``links`` is an optional list of ``(trace_id, span_id)`` pairs for
    work this span covers without parenting it — a group-commit fsync
    names every request whose records it made durable, a batched kernel
    launch names every document's originating request. The active
    cross-process trace id (``trace_scope``) is recorded automatically.
    """

    __slots__ = ("name", "labels", "fields", "links", "t0",
                 "_id", "_parent", "_token")

    def __init__(self, name: str, labels: Optional[dict] = None,
                 links=None, **fields):
        self.name = name
        self.labels = labels
        self.fields = fields
        self.links = links
        self.t0 = 0.0

    @property
    def span_id(self) -> int:
        """This span's id (valid once entered) — what a forwarded trace
        context names as the remote parent."""
        return self._id

    def __enter__(self):
        self._parent = current_span.get()
        self._id = next_span_id()
        self._token = current_span.set(self._id)
        if cycle_enter is not None:
            cycle_enter(self.name)
        self.t0 = _perf_counter()
        return self

    def __exit__(self, etype, evalue, tb):
        t1 = _perf_counter()
        dur = t1 - self.t0
        current_span.reset(self._token)
        name = self.name
        if cycle_exit is not None:
            cycle_exit(name, dur)
        with registry.lock:
            slot = legacy_timings.get(name)
            if slot is None:
                legacy_timings[name] = [dur, 1]
            else:
                slot[0] += dur
                slot[1] += 1
            registry._get_locked(
                name, "histogram", self.labels or {}
            )._observe_locked(dur)
        if recorder.capacity > 0:
            dropped = recorder.record(SpanRecord(
                name, self._id, self._parent, self.t0 - _ORIGIN, dur,
                threading.get_ident(), self.fields,
                "error" if etype is not None else "ok",
                current_trace.get(),
                tuple(self.links) if self.links else None,
            ))
            if dropped:
                # the ring wrapping silently was invisible before: count
                # it so a truncated flight dump advertises itself
                with registry.lock:
                    registry._get_locked(
                        "obs.spans_dropped", "counter", {})._inc_locked(1)
        if logger.isEnabledFor(_DEBUG):
            event(name, ms=round(dur * 1e3, 3),
                  **(self.labels or {}), **self.fields)
        return False


class trace_scope:
    """Activate a cross-process trace context: ``with
    obs.trace_scope(trace_id, parent_span_id):`` makes every span opened
    inside record that trace id, with the (remote) parent span id as the
    root of the local parent chain. Invalid or absent ids deactivate the
    scope entirely — hostile wire input degrades to "no trace", never an
    error — and with no scope active the cost a span pays is a single
    contextvar read."""

    __slots__ = ("trace_id", "parent", "_t_token", "_s_token")

    def __init__(self, trace_id, parent_span_id=None):
        self.trace_id = (
            trace_id
            if isinstance(trace_id, str) and 0 < len(trace_id) <= 128
            else None
        )
        self.parent = (
            parent_span_id
            if isinstance(parent_span_id, int)
            and not isinstance(parent_span_id, bool)
            else None
        )
        self._t_token = None
        self._s_token = None

    def __enter__(self):
        if self.trace_id is not None:
            self._t_token = current_trace.set(self.trace_id)
            if self.parent is not None:
                self._s_token = current_span.set(self.parent)
        return self

    def __exit__(self, *exc):
        if self._t_token is not None:
            current_trace.reset(self._t_token)
            self._t_token = None
            if self._s_token is not None:
                current_span.reset(self._s_token)
                self._s_token = None
        return False


def current_trace_context() -> Optional[tuple]:
    """``(trace_id, active_span_id)`` when a propagated trace is active,
    else None — what gets captured into journal appends and batcher
    stages so later group-commit/batched spans can link back."""
    tid = current_trace.get()
    if tid is None:
        return None
    return (tid, current_span.get())


def decode_wire_traces(v, limit: int = 16) -> list:
    """Sanitize a wire-supplied ``traces`` list (``[[trace_id,
    span_id], ...]``) into span-link tuples; anything malformed is
    silently dropped (hostile input must degrade, not raise)."""
    out = []
    if isinstance(v, (list, tuple)):
        for e in v[:limit]:
            if (
                isinstance(e, (list, tuple)) and len(e) == 2
                and isinstance(e[0], str) and 0 < len(e[0]) <= 128
                and (e[1] is None
                     or (isinstance(e[1], int) and not isinstance(e[1], bool)))
            ):
                out.append((e[0], e[1]))
    return out


def export_trace(path: str) -> int:
    """Dump the span ring buffer as Chrome-trace/Perfetto JSON; returns
    the number of span events written. Open the file at
    https://ui.perfetto.dev (or chrome://tracing)."""
    return recorder.export_chrome_trace(path)


def render_prometheus() -> str:
    return registry.render_prometheus()


def snapshot() -> list:
    return registry.snapshot()


def reset_all() -> None:
    """Full reset (tests): registry, legacy views and the span buffer."""
    with registry.lock:
        registry.reset()
        legacy_counters.clear()
        legacy_timings.clear()
    recorder.clear()
