"""Flight recorder: bounded in-memory history, dumped on exit/failover.

Every process keeps cheap bounded rings of what just happened — the span
ring (obs/spans.py ``SpanRecorder``), a ring of structured events
(``obs.event``), and a ring of metric deltas (every ``obs.count`` /
``gauge_set`` / ``observe`` call) — plus RTT clock-sync samples against
its peers. ``dump()`` writes all of it, with a full metrics snapshot, as
one JSON file; the intended triggers are process exit (``install()``
registers an atexit hook, which also covers a handled SIGTERM and an
unhandled crash), and explicit postmortem moments like a router
failover.

``merge_flights()`` stitches several processes' dumps into one
Perfetto/Chrome-trace timeline. Clocks align in two layers:

* every dump carries ``origin_wall`` — the wall-clock time of that
  process's monotonic span origin — which lines up processes on one
  host;
* RTT samples (``note_clock_sync``: request send/receive times around a
  peer's reported monotonic "now", e.g. the leader's ``replPing`` round
  trips and the router's ``clusterStatus`` polls) refine the offset
  NTP-style from the RTT midpoint, and propagate transitively
  (router -> leader -> follower) from the first dump as reference.

Cross-process span identity needs no rewriting: span ids are minted from
a process-random base (obs/spans.py), so a child's ``parent_id`` (or a
group-commit span's ``links``) in one dump resolves against a span in
another dump directly.

Env knobs: ``AUTOMERGE_TPU_FLIGHT_BUFFER`` sizes the event/delta rings
(default 2048; 0 disables their recording — the span ring has its own
``AUTOMERGE_TPU_SPAN_BUFFER``), ``AUTOMERGE_TPU_FLIGHT_DIR`` makes the
server entry points (``rpc.main``, the cluster router) install the
recorder at startup.
"""

from __future__ import annotations

import atexit
import json
import os
import re
import signal
import statistics
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from .spans import now as _mono_now

_SAFE_NAME = re.compile(r"[^A-Za-z0-9._-]")


class FlightRecorder:
    """Bounded recent-history rings + dump/install. One per process,
    constructed by ``obs/__init__`` around the global span recorder and
    metrics registry."""

    def __init__(self, span_recorder, registry, capacity: Optional[int] = None):
        if capacity is None:
            try:
                capacity = int(
                    os.environ.get("AUTOMERGE_TPU_FLIGHT_BUFFER", "2048"))
            except ValueError:
                capacity = 2048
        self.capacity = max(capacity, 0)
        self._spans = span_recorder
        self._registry = registry
        self.events: deque = deque(maxlen=max(self.capacity, 1))
        self.deltas: deque = deque(maxlen=max(self.capacity, 1))
        self.clock_sync: deque = deque(maxlen=256)
        self.node_id: Optional[str] = None
        self.dir: Optional[str] = None
        self._lock = threading.Lock()
        self._seq = 0
        self._installed = False
        self._signal_installed = False
        # optional callable returning the history-ring status dict
        # (obs/history.py sets it when the sampler starts) so every
        # dump carries the metric trend that led up to it
        self.history_provider = None

    # -- recording (hot-ish paths: one deque append, no locks) ---------------

    def note_event(self, name: str, fields: dict) -> None:
        if self.capacity:
            self.events.append((_mono_now(), name, dict(fields)))

    def note_delta(self, kind: str, name: str,
                   labels: Optional[dict], value) -> None:
        if self.capacity:
            self.deltas.append(
                (_mono_now(), kind, name,
                 dict(labels) if labels else None, value))

    def note_clock_sync(self, peer: str, t_send: float, t_recv: float,
                        peer_now: float) -> None:
        """One RTT sample against ``peer``: our monotonic clock read
        before/after a round trip whose response carried the peer's own
        monotonic ``now``. The midpoint estimates simultaneity."""
        self.clock_sync.append(
            {"peer": str(peer), "t_send": t_send, "t_recv": t_recv,
             "peer_now": peer_now})

    # -- lifecycle -----------------------------------------------------------

    def install(self, directory: str, node_id: str) -> None:
        """Dump into ``directory`` as ``flight-<node_id>-<pid>-<n>.json``
        on process exit (atexit covers clean exits, handled SIGTERM and
        crash-unwinds) and on SIGUSR2 (a wedged-but-alive node can be
        snapshotted without killing it); explicit ``dump()`` calls
        (failover) also land there. Idempotent."""
        os.makedirs(directory, exist_ok=True)
        self.dir = directory
        self.node_id = _SAFE_NAME.sub("_", str(node_id))[:64] or "proc"
        if not self._installed:
            self._installed = True
            atexit.register(self._atexit_dump)
        if not self._signal_installed:
            # only the main thread may set handlers; an embedding that
            # installs from a worker thread just skips the signal hook
            try:
                signal.signal(signal.SIGUSR2, self._on_sigusr2)
                self._signal_installed = True
            except (ValueError, AttributeError, OSError):
                pass

    def _on_sigusr2(self, signum, frame) -> None:
        try:
            self.dump(reason="signal")
        except Exception:  # noqa: BLE001 — a probe must not kill the node
            pass

    def _atexit_dump(self) -> None:
        try:
            self.dump(reason="exit")
        except Exception:  # noqa: BLE001 — dying must not die harder
            pass

    # -- dumping -------------------------------------------------------------

    def dump(self, path: Optional[str] = None,
             reason: str = "manual") -> Optional[str]:
        """Write the flight data as one JSON file; returns the path, or
        None when no explicit path was given and ``install()`` never
        configured a directory."""
        if path is None:
            if self.dir is None:
                return None
            with self._lock:
                self._seq += 1
                seq = self._seq
            path = os.path.join(
                self.dir,
                f"flight-{self.node_id}-{os.getpid()}-{seq}.json")
        mono = _mono_now()
        doc = {
            "format": "automerge_tpu-flight-v1",
            "node_id": self.node_id or f"pid{os.getpid()}",
            "pid": os.getpid(),
            "reason": reason,
            # wall-clock instant of this process's monotonic origin: the
            # coarse cross-process alignment (RTT samples refine it)
            "origin_wall": time.time() - mono,
            "dumped_at_mono": mono,
            "spans": [r.to_dict() for r in self._spans.snapshot()],
            "events": [
                {"t": t, "name": n, "fields": f}
                for t, n, f in list(self.events)
            ],
            "metric_deltas": [
                {"t": t, "kind": k, "name": n, "labels": lb, "value": v}
                for t, k, n, lb, v in list(self.deltas)
            ],
            "metrics": self._registry.snapshot(),
            "clock_sync": list(self.clock_sync),
        }
        if self.history_provider is not None:
            try:
                doc["history"] = self.history_provider()
            except Exception:  # noqa: BLE001 — trend data is best-effort
                pass
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path


# -- multi-process merge ------------------------------------------------------


def _rtt_offsets(dumps: List[dict]) -> Dict[str, float]:
    """``origin_wall`` per node, refined transitively from RTT samples.

    A sample in dump A about peer B says: B's monotonic clock read
    ``peer_now`` at A-monotonic midpoint ``m`` — so B's origin happened
    at wall time ``wall_A(m) - peer_now = A.origin_wall + m - peer_now``
    (median over samples). The BFS roots at the dump holding the most
    samples (the router in a full cluster — it probes every leader; a
    leader otherwise — it pings its followers), so router -> leader ->
    follower chains align even when only adjacent pairs exchanged
    pings. Samplers are only ever on the probing side, so rooting at an
    unsampled follower would reach nobody. Nodes no sample chain
    reaches keep their self-reported ``origin_wall``."""
    by_node = {d["node_id"]: d for d in dumps}
    origin = {n: d.get("origin_wall", 0.0) for n, d in by_node.items()}
    root = max(
        by_node, key=lambda n: len(by_node[n].get("clock_sync", ())),
        default=None,
    )
    frontier = [root] if root is not None else []
    visited = set(frontier)
    while frontier:
        nxt: List[str] = []
        for node in frontier:
            samples: Dict[str, List[float]] = {}
            for s in by_node[node].get("clock_sync", ()):
                peer = s.get("peer")
                if peer not in by_node or peer in visited:
                    continue
                m = (s["t_send"] + s["t_recv"]) / 2.0
                samples.setdefault(peer, []).append(
                    origin[node] + m - s["peer_now"])
            for peer, ests in samples.items():
                origin[peer] = statistics.median(ests)
                visited.add(peer)
                nxt.append(peer)
        frontier = nxt
    return origin


def merge_flights(paths: List[str]) -> Tuple[dict, dict]:
    """Stitch flight dumps into one Chrome-trace/Perfetto document.

    Returns ``(trace_doc, info)``: the trace has one pid per process
    (named by node id), every span as a complete ("X") event on the
    clock-aligned shared timeline, and every recorded flight event as an
    instant ("i") event. Span/parent/link ids pass through untouched —
    they are globally unique — so one propagated trace renders as a
    connected request across processes."""
    raw = []
    for p in paths:
        with open(p) as f:
            d = json.load(f)
        if d.get("format") != "automerge_tpu-flight-v1":
            raise ValueError(f"{p}: not a flight dump")
        raw.append(d)
    if not raw:
        raise ValueError("no flight dumps to merge")
    # one process may dump several times (a router dumps at failover AND
    # exit) under one node id, with overlapping span rings: collapse to
    # one dump per node — union spans by span_id and events by identity,
    # latest dump's metadata wins — so a span renders once, under one pid
    by_node_order: List[str] = []
    merged_dumps: Dict[str, dict] = {}
    for d in sorted(raw, key=lambda d: d.get("dumped_at_mono", 0.0)):
        node = d["node_id"]
        prev = merged_dumps.get(node)
        if prev is None:
            by_node_order.append(node)
            merged_dumps[node] = d
            continue
        spans = {s["span_id"]: s for s in prev["spans"]}
        spans.update((s["span_id"], s) for s in d["spans"])
        events = {(e["t"], e["name"]): e
                  for e in prev.get("events", ())}
        events.update(((e["t"], e["name"]), e)
                      for e in d.get("events", ()))
        d = dict(d)
        d["spans"] = sorted(spans.values(), key=lambda s: s["start"])
        d["events"] = sorted(events.values(), key=lambda e: e["t"])
        d["clock_sync"] = list(prev.get("clock_sync", ())) + list(
            d.get("clock_sync", ()))
        merged_dumps[node] = d
    dumps = [merged_dumps[n] for n in by_node_order]
    origin = _rtt_offsets(dumps)
    t0 = min(
        origin[d["node_id"]] + s["start"]
        for d in dumps for s in d["spans"]
    ) if any(d["spans"] for d in dumps) else min(origin.values())

    events: List[dict] = []
    for pid, d in enumerate(dumps, start=1):
        ow = origin[d["node_id"]]
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": d["node_id"]},
        })
        for s in d["spans"]:
            args = dict(s.get("fields") or {})
            args["span_id"] = s["span_id"]
            if s.get("parent_id") is not None:
                args["parent_id"] = s["parent_id"]
            if s.get("trace_id") is not None:
                args["trace_id"] = s["trace_id"]
            if s.get("links"):
                args["links"] = s["links"]
            if s.get("status", "ok") != "ok":
                args["status"] = s["status"]
            events.append({
                "name": s["name"], "cat": "automerge_tpu", "ph": "X",
                "ts": round((ow + s["start"] - t0) * 1e6, 3),
                "dur": round(s["duration"] * 1e6, 3),
                "pid": pid, "tid": s.get("thread_id", 0),
                "args": args,
            })
        for e in d.get("events", ()):
            events.append({
                "name": e["name"], "cat": "automerge_tpu.event", "ph": "i",
                "ts": round((ow + e["t"] - t0) * 1e6, 3),
                "pid": pid, "tid": 0, "s": "p",
                "args": dict(e.get("fields") or {}),
            })
    events.sort(key=lambda e: e.get("ts", -1))
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"producer": "automerge_tpu.obs.flight"},
    }
    info = {
        "processes": {
            d["node_id"]: {
                "pid": i + 1,
                "spans": len(d["spans"]),
                "events": len(d.get("events", ())),
                "aligned": (
                    "rtt" if abs(origin[d["node_id"]]
                                 - d.get("origin_wall", 0.0)) > 1e-12
                    else "wall"
                ),
            }
            for i, d in enumerate(dumps)
        },
        "spans": sum(len(d["spans"]) for d in dumps),
    }
    return doc, info
