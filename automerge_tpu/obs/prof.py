"""Drain-cycle performance observatory: per-cycle stage attribution.

The spans in obs/__init__ are per-*call*: one ``device.kernel`` span per
launch, one ``journal.fsync`` per sync. What no layer provided until now
is the per-*cycle* view — for one drain of the serve pool (or one
``apply_cross_doc`` pass in the bench), where did the wall clock go?
Host staging (dedup, causal ordering, column splice, delta resolution),
the device pipeline (pack, h2d, kernel, linearize, readback, scatter),
or durability (the covering group-commit fsync)? That attribution is
what decides which ROADMAP perf item to spend next (the host append
phase is the claimed ceiling — this module is the instrument that can
prove or retire that claim, and watch it regress).

Mechanics: ``with prof.cycle(kind=..., docs=..., doc=...)`` activates a
contextvar collector for the calling context. Two hooks installed into
``obs.span`` (``cycle_enter``/``cycle_exit``; a no-op global check when
this module was never imported, a contextvar read when idle) feed every
span completed inside the cycle into a fixed stage taxonomy:

* **host** — ``device.stage.dedup`` / ``device.stage.causal_order``
  (the ``_take_ready`` halves), ``device.apply`` (the staging umbrella,
  whose interior breaks down into ``device.stage.splice``,
  ``device.materialize``, ``device.delta_resolve``, ``device.extract``);
* **device** — ``device.pack``, ``device.h2d``, ``device.kernel``,
  ``device.linearize``, ``device.readback``, ``device.scatter``,
  ``device.mesh_resolve``;
* **fsync** — ``journal.fsync`` (the group-commit share of a serve
  drain's ack path).

Nesting is handled: a parent span (``device.apply``) counts toward the
attributed total exactly once; stages completing inside it land in the
breakdown table without double-counting the total, and device stages
nested under a host umbrella (the per-doc fallback path launches a
kernel *inside* ``device.apply``) are re-assigned to the device side of
the split without inflating the sum. ``attributed_frac`` is therefore a
real fraction of the measured cycle wall clock.

Each finished cycle:

* merges into the process-wide ``profiler`` aggregate (totals, a
  bounded top-K expensive-docs table, occupancy/launch counts);
* feeds fixed-cardinality histograms — ``drain.stage_seconds{stage=}``
  (one label per taxonomy stage), ``drain.attributed_fraction``,
  ``drain.occupancy``, ``drain.docs_per_launch`` — scrapeable like any
  other instrument;
* lands in the flight recorder as a ``drain.cycle_report`` event, so an
  offline ``perf-report`` can rebuild the whole aggregate from a merged
  flight dump of a dead (or remote) process.

Surfaces: the ``perfStatus`` RPC and ``python -m automerge_tpu
perf-report`` render ``profiler.status()`` — a host-vs-device
percentage breakdown with occupancy, docs-per-launch, queue-wait and
fsync share. ``profileStart`` / ``profileStop`` additionally capture a
``jax.profiler`` device trace with a named annotation
(``prof.annotate``) wrapped around every kernel-launch site; on boxes
where the profiler backend is unavailable they degrade to an
``{"ok": false}`` answer, never an error (the ``enable_mesh``
contract).

Env knobs: ``AUTOMERGE_TPU_PROF=0`` disarms cycle collection entirely
(cycles become no-ops); ``AUTOMERGE_TPU_PROF_TOPK`` sizes the
expensive-docs table (default 8; the working set is bounded at 4x that
before pruning).
"""

from __future__ import annotations

import contextvars
import os
import threading
from contextlib import nullcontext
from time import perf_counter
from typing import Dict, List, Optional

import automerge_tpu.obs as _obs
from . import heat as _heat

# -- stage taxonomy -----------------------------------------------------------

# span name -> (stage key, side). Fixed cardinality by construction: the
# histogram label set below can never exceed this table.
STAGES: Dict[str, tuple] = {
    "device.stage.dedup": ("dedup", "host"),
    "device.stage.causal_order": ("causal_order", "host"),
    "device.stage.splice": ("splice", "host"),
    # the vectorized cross-doc staging passes (ops/host_batch.py): one
    # span each per drain, covering every packed document at once
    "host.pack": ("host_pack", "host"),
    "host.sort": ("host_sort", "host"),
    "device.materialize": ("materialize", "host"),
    "device.delta_resolve": ("delta_resolve", "host"),
    "device.extract": ("extract", "host"),
    "device.pack": ("pack", "device"),
    "device.h2d": ("h2d", "device"),
    # the eager run-table expansion dispatch (merge.stage_cols_device's
    # _expander) — its own row so the run-native kernels' win (expansion
    # fused INTO the kernel, this stage -> 0) is visible, not folded
    # into h2d
    "device.expand": ("expand", "device"),
    "device.kernel": ("kernel", "device"),
    "device.linearize": ("linearize", "device"),
    "device.readback": ("readback", "device"),
    "device.scatter": ("scatter", "device"),
    "device.mesh_resolve": ("mesh", "device"),
    "serve.write": ("write", "host"),
    "journal.fsync": ("fsync", "fsync"),
}

# umbrella spans: their own duration attributes to a side exactly once
# (when they close at cycle top level); everything that completed inside
# them stays breakdown-only. device.stage.splice is both a stage row and
# a parent (device.extract runs inside it); device.batched wraps the
# whole packed pack/launch/scatter region so its glue attributes too;
# rpc.request makes a serve drain's request-handling wall attributable
# (a put/commit drain is mostly dispatch, not device work — without
# this umbrella a live perfStatus would claim the drain went nowhere).
PARENTS: Dict[str, tuple] = {
    "device.apply": (None, "host"),
    "device.stage.splice": ("splice", "host"),
    "device.batched": (None, "device"),
    "rpc.request": (None, "host"),
    # the cross-doc splice is a stage row AND a parent umbrella, so any
    # span a future splice internals nests stays breakdown-only
    "host.splice": ("host_splice", "host"),
}

# host breakdown rows that partition the host side without overlapping
# each other (extract lives inside splice, so it is excluded): host_other
# in a report is host - sum(these) - nested device time
_HOST_EXCLUSIVE = ("dedup", "causal_order", "splice", "materialize",
                   "delta_resolve", "write", "host_pack", "host_sort",
                   "host_splice")

_NOTE_KEYS = ("useful_rows", "padded_rows", "launches", "docs", "changes",
              "h2d_bytes", "h2d_dense_bytes", "overlap_s")


class _Cycle:
    """The per-cycle collector the span hooks feed."""

    __slots__ = ("kind", "t0", "parents", "stages", "host_s", "device_s",
                 "fsync_s", "nested_device_s", "notes", "doc_costs", "doc")

    def __init__(self, kind: str, docs: int = 0, doc: Optional[str] = None):
        self.kind = kind
        self.t0 = perf_counter()
        self.parents: List[str] = []  # sides of the open umbrella spans
        self.stages: Dict[str, float] = {}
        self.host_s = 0.0
        self.device_s = 0.0
        self.fsync_s = 0.0
        self.nested_device_s = 0.0
        self.notes = dict.fromkeys(_NOTE_KEYS, 0)
        if docs:
            self.notes["docs"] = docs
        self.doc_costs: Dict[str, float] = {}
        self.doc = doc  # attribute the whole cycle wall to this doc

    def _side(self, side: str, dur: float) -> None:
        if side == "host":
            self.host_s += dur
        elif side == "device":
            self.device_s += dur
        else:
            self.fsync_s += dur

    def span_enter(self, name: str) -> None:
        parent = PARENTS.get(name)
        if parent is not None:
            self.parents.append(parent[1])

    def span_exit(self, name: str, dur: float) -> None:
        parent = PARENTS.get(name)
        ks = STAGES.get(name) if parent is None else None
        if parent is None and ks is None:
            return
        # a span ENTERED before this cycle started may exit inside it
        # (an rpc.request umbrella already open when a nested cycle
        # begins): only the portion that overlaps the cycle attributes,
        # or attributed_s could exceed the cycle wall
        elapsed = perf_counter() - self.t0
        if dur > elapsed:
            dur = elapsed
        if parent is not None:
            key, side = parent
            if self.parents:
                self.parents.pop()
            if key is not None:
                self.stages[key] = self.stages.get(key, 0.0) + dur
            if not self.parents:
                self._side(side, dur)
            elif side == "device" and self.parents[-1] == "host":
                # a device umbrella (device.batched) nested under a host
                # one (rpc.request on a live accelerator serve drain):
                # its whole region is device work the split must move
                # out of the host share — its own children skipped the
                # reassignment because THEIR innermost parent is device
                self.nested_device_s += dur
            return
        key, side = ks
        self.stages[key] = self.stages.get(key, 0.0) + dur
        if not self.parents:
            self._side(side, dur)
        elif side == "device" and self.parents[-1] == "host":
            # a kernel launched inside the host umbrella (the per-doc
            # fallback path): keep the sum honest, reassign in the split
            self.nested_device_s += dur

    def note(self, key: str, v) -> None:
        self.notes[key] = self.notes.get(key, 0) + v

    def note_doc(self, name: str, seconds: float) -> None:
        self.doc_costs[name] = self.doc_costs.get(name, 0.0) + seconds

    def finish(self) -> dict:
        wall = perf_counter() - self.t0
        attributed = self.host_s + self.device_s + self.fsync_s
        if self.doc is not None:
            # the cycle's own doc gets the WHOLE wall — but staging
            # seconds note_doc'd for the same doc inside this cycle are
            # part of that wall, so take the max instead of summing
            # (a serve drain must not rank its doc twice as expensive)
            self.doc_costs[self.doc] = max(
                self.doc_costs.get(self.doc, 0.0), wall
            )
        n = self.notes
        useful, padded = n["useful_rows"], n["padded_rows"]
        return {
            "kind": self.kind,
            "wall_s": wall,
            "attributed_s": attributed,
            "attributed_frac": min(attributed / wall, 1.0) if wall > 0 else 0.0,
            # the split reassigns device work that ran nested under the
            # host umbrella, so host_s is PURE host time
            "host_s": max(self.host_s - self.nested_device_s, 0.0),
            "device_s": self.device_s + self.nested_device_s,
            "fsync_s": self.fsync_s,
            "stages": dict(self.stages),
            "docs": n["docs"],
            "changes": n["changes"],
            "launches": n["launches"],
            "useful_rows": useful,
            "padded_rows": padded,
            "occupancy": (
                useful / (useful + padded) if (useful + padded) else None
            ),
            # h2d byte accounting (merge._note_h2d): actual bytes staged
            # vs their dense equivalent — the compressed-residency win
            "h2d_bytes": n["h2d_bytes"],
            "h2d_dense_bytes": n["h2d_dense_bytes"],
            # host seconds spent while a dispatched device launch was
            # still in flight (the double-buffered drain pipeline notes
            # them at its dispatch/collect seam) — wall hidden behind
            # the kernel rather than serialized after it
            "overlap_s": n["overlap_s"],
            "overlap_frac": (
                min(n["overlap_s"] / wall, 1.0) if wall > 0 else 0.0
            ),
            "doc_costs": dict(self.doc_costs),
        }


_CUR: contextvars.ContextVar[Optional[_Cycle]] = contextvars.ContextVar(
    "automerge_tpu_prof_cycle", default=None
)


# -- the span hooks (installed into obs at import) ---------------------------


def _hook_enter(name: str) -> None:
    c = _CUR.get()
    if c is not None:
        c.span_enter(name)


def _hook_exit(name: str, dur: float) -> None:
    c = _CUR.get()
    if c is not None:
        c.span_exit(name, dur)


_obs.cycle_enter = _hook_enter
_obs.cycle_exit = _hook_exit


def note(key: str, v=1) -> None:
    """Deposit a numeric fact (rows, launches, docs) into the active
    cycle; a no-op outside any cycle. Instrumented sites call this next
    to their obs counters so per-cycle occupancy/launch figures exist
    without a racy global-counter diff."""
    c = _CUR.get()
    if c is not None:
        c.note(key, v)


def note_doc(name: Optional[str], seconds: float) -> None:
    """Attribute ``seconds`` of the active cycle to a document (by its
    durable name or a synthetic label) — feeds the top-K table."""
    c = _CUR.get()
    if c is not None and name:
        c.note_doc(str(name), seconds)


# -- the process-wide aggregate ----------------------------------------------


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class CycleProfiler:
    """Process-wide aggregate of finished cycle reports, plus the
    bounded top-K expensive-docs table. Thread-safe; one exists
    (``prof.profiler``)."""

    def __init__(self, top_k: Optional[int] = None):
        self.enabled = os.environ.get("AUTOMERGE_TPU_PROF", "1") != "0"
        self.top_k = top_k or _env_int("AUTOMERGE_TPU_PROF_TOPK", 8)
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.cycles = 0
            self.wall_s = 0.0
            self.attributed_s = 0.0
            self.host_s = 0.0
            self.device_s = 0.0
            self.fsync_s = 0.0
            self.stage_s: Dict[str, float] = {}
            self.useful_rows = 0
            self.padded_rows = 0
            self.launches = 0
            self.docs = 0
            self.changes = 0
            self.h2d_bytes = 0
            self.h2d_dense_bytes = 0
            self.overlap_s = 0.0
            self._doc_costs: Dict[str, float] = {}

    def record(self, report: dict) -> None:
        """Merge one finished cycle; export the fixed-cardinality
        histograms and the flight-recorder event."""
        with self._lock:
            self.cycles += 1
            self.wall_s += report["wall_s"]
            self.attributed_s += report["attributed_s"]
            self.host_s += report["host_s"]
            self.device_s += report["device_s"]
            self.fsync_s += report["fsync_s"]
            for k, v in report["stages"].items():
                self.stage_s[k] = self.stage_s.get(k, 0.0) + v
            self.useful_rows += report["useful_rows"]
            self.padded_rows += report["padded_rows"]
            self.launches += report["launches"]
            self.docs += report["docs"]
            self.changes += report["changes"]
            self.h2d_bytes += report.get("h2d_bytes", 0)
            self.h2d_dense_bytes += report.get("h2d_dense_bytes", 0)
            self.overlap_s += report.get("overlap_s", 0.0)
            for d, s in report["doc_costs"].items():
                self._doc_costs[d] = self._doc_costs.get(d, 0.0) + s
            # bounded: past 4x the table prunes to the K most expensive
            # (space-saving flavor — a consistently cheap doc may rotate
            # out, a whale never does)
            if len(self._doc_costs) > 4 * self.top_k:
                keep = sorted(
                    self._doc_costs.items(), key=lambda kv: -kv[1]
                )[: self.top_k]
                self._doc_costs = dict(keep)
        # attributed drain seconds are the cost half of the heat signal:
        # a doc can be request-cold but drain-expensive (huge merges),
        # and the advisor needs to see that
        for d, s in report["doc_costs"].items():
            _heat.note(d, "drain_s", s)
        _obs.observe("drain.attributed_fraction", report["attributed_frac"])
        _obs.observe("drain.overlap_fraction", report.get("overlap_frac", 0.0))
        for k, v in report["stages"].items():
            _obs.observe("drain.stage_seconds", v, labels={"stage": k})
        if report["occupancy"] is not None:
            _obs.observe("drain.occupancy", report["occupancy"])
        if report["launches"]:
            _obs.observe(
                "drain.docs_per_launch", report["docs"] / report["launches"]
            )
        ev = {
            "kind": report["kind"],
            "wall_s": round(report["wall_s"], 6),
            "attributed_s": round(report["attributed_s"], 6),
            "host_s": round(report["host_s"], 6),
            "device_s": round(report["device_s"], 6),
            "fsync_s": round(report["fsync_s"], 6),
            "docs": report["docs"],
            "changes": report["changes"],
            "launches": report["launches"],
            "useful_rows": report["useful_rows"],
            "padded_rows": report["padded_rows"],
            "h2d_bytes": report.get("h2d_bytes", 0),
            "h2d_dense_bytes": report.get("h2d_dense_bytes", 0),
            "overlap_s": round(report.get("overlap_s", 0.0), 6),
        }
        for k, v in report["stages"].items():
            ev[f"stage_{k}_s"] = round(v, 6)
        _obs.event("drain.cycle_report", **ev)

    def top_docs(self, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            items = sorted(self._doc_costs.items(), key=lambda kv: -kv[1])
        return [
            {"doc": d, "seconds": round(s, 6)}
            for d, s in items[: n or self.top_k]
        ]

    def status(self, top: Optional[int] = None) -> dict:
        """The merged report the ``perfStatus`` RPC / ``perf-report``
        CLI render: cumulative stage attribution with a host/device
        split, occupancy, docs-per-launch, queue-wait and drain-cycle
        percentiles, and the top-K expensive-docs table."""
        with self._lock:
            agg = {
                "cycles": self.cycles,
                "wall_s": self.wall_s,
                "attributed_s": self.attributed_s,
                "host_s": self.host_s,
                "device_s": self.device_s,
                "fsync_s": self.fsync_s,
                "stages": dict(self.stage_s),
                "useful_rows": self.useful_rows,
                "padded_rows": self.padded_rows,
                "launches": self.launches,
                "docs": self.docs,
                "changes": self.changes,
                "h2d_bytes": self.h2d_bytes,
                "h2d_dense_bytes": self.h2d_dense_bytes,
                "overlap_s": self.overlap_s,
            }
        out = summarize(agg)
        out["enabled"] = self.enabled
        out["jax_profiler"] = dict(_jax_trace)
        out["top_docs"] = self.top_docs(top)
        # extraction-cache efficacy: extract is a named dominant host
        # stage, and hit ratio is what separates "re-decoding the same
        # changes" from real staging work (None = never consulted)
        hits = _obs.counter_values("extract.change_cache_hit", "").get("", 0)
        misses = _obs.counter_values(
            "extract.change_cache_miss", "").get("", 0)
        out["extract_cache"] = {
            "hits": hits,
            "misses": misses,
            "cache_hit_ratio": (
                round(hits / (hits + misses), 4) if (hits + misses) else None
            ),
        }
        # the heat observatory rides along so one perfStatus answer (and
        # one offline perf-report) shows cost AND demand per document
        out["heat"] = _heat.snapshot(top=top or self.top_k)
        out["drain_cycle_seconds"] = {
            f"p{int(q * 100)}": round(v, 6)
            for q, v in _obs.percentiles("drain.cycle_seconds").items()
        }
        out["queue_wait_seconds"] = {
            f"p{int(q * 100)}": round(v, 6)
            for q, v in _obs.percentiles("serve.queue_wait").items()
        }
        return out


def summarize(agg: dict) -> dict:
    """Percentage view over cumulative cycle totals — shared by the live
    ``profiler.status()`` and the offline flight-dump reducer, so both
    surfaces render the identical shape."""
    wall = agg["wall_s"]
    attributed = agg["attributed_s"]
    split_total = agg["host_s"] + agg["device_s"] + agg["fsync_s"]
    useful, padded = agg["useful_rows"], agg["padded_rows"]
    stages = agg["stages"]
    host_other = max(
        agg["host_s"]
        - sum(stages.get(k, 0.0) for k in _HOST_EXCLUSIVE),
        0.0,
    )
    pct = lambda x, of: round(100.0 * x / of, 1) if of > 0 else 0.0  # noqa: E731
    return {
        "cycles": agg["cycles"],
        "wall_s": round(wall, 6),
        "attributed_s": round(attributed, 6),
        "attributed_frac": (
            round(min(attributed / wall, 1.0), 4) if wall > 0 else 0.0
        ),
        "host_pct": pct(agg["host_s"], split_total),
        "device_pct": pct(agg["device_s"], split_total),
        "fsync_pct": pct(agg["fsync_s"], split_total),
        "host_s": round(agg["host_s"], 6),
        "device_s": round(agg["device_s"], 6),
        "fsync_s": round(agg["fsync_s"], 6),
        "host_other_s": round(host_other, 6),
        "stages": {
            k: {"seconds": round(v, 6), "pct_of_wall": pct(v, wall)}
            for k, v in sorted(stages.items(), key=lambda kv: -kv[1])
        },
        "occupancy": (
            round(useful / (useful + padded), 4) if (useful + padded) else None
        ),
        "useful_rows": useful,
        "padded_rows": padded,
        # h2d byte accounting across the cycles: actual staged bytes vs
        # dense equivalent — the compressed-residency h2d win as a ratio
        "h2d_bytes": agg.get("h2d_bytes", 0),
        "h2d_dense_bytes": agg.get("h2d_dense_bytes", 0),
        "h2d_compress_ratio": (
            round(agg.get("h2d_dense_bytes", 0) / agg["h2d_bytes"], 2)
            if agg.get("h2d_bytes") else None
        ),
        # pipelined-drain overlap: host seconds that ran while a device
        # launch was in flight, as a fraction of the drain wall (0 = the
        # two halves serialized, -> 1 = wall collapsed to max(host, device))
        "overlap_s": round(agg.get("overlap_s", 0.0), 6),
        "overlap_fraction": (
            round(min(agg.get("overlap_s", 0.0) / wall, 1.0), 4)
            if wall > 0 else 0.0
        ),
        "launches": agg["launches"],
        "docs": agg["docs"],
        "changes": agg["changes"],
        "docs_per_launch": (
            round(agg["docs"] / agg["launches"], 2) if agg["launches"] else None
        ),
    }


profiler = CycleProfiler()


class cycle:
    """``with prof.cycle(kind="serve", doc=name):`` — collect every span
    the calling context completes until exit, then fold the report into
    the process aggregate. ``self.report`` holds the finished report
    after exit (None when profiling is disarmed). Re-entrant: an inner
    cycle shadows the outer for its duration."""

    __slots__ = ("kind", "docs", "doc", "_c", "_tok", "report")

    def __init__(self, kind: str = "drain", docs: int = 0,
                 doc: Optional[str] = None):
        self.kind = kind
        self.docs = docs
        self.doc = doc
        self.report = None

    def __enter__(self):
        if not profiler.enabled:
            self._tok = None
            return self
        self._c = _Cycle(self.kind, docs=self.docs, doc=self.doc)
        self._tok = _CUR.set(self._c)
        return self

    def __exit__(self, *exc):
        if self._tok is None:
            return False
        _CUR.reset(self._tok)
        self.report = self._c.finish()
        profiler.record(self.report)
        return False


def summarize_reports(reports: List[dict]) -> dict:
    """Reduce raw cycle reports (e.g. one bench config's drains) into
    the same summary shape ``profiler.status()`` serves."""
    agg = {
        "cycles": 0, "wall_s": 0.0, "attributed_s": 0.0, "host_s": 0.0,
        "device_s": 0.0, "fsync_s": 0.0, "stages": {}, "useful_rows": 0,
        "padded_rows": 0, "launches": 0, "docs": 0, "changes": 0,
        "h2d_bytes": 0, "h2d_dense_bytes": 0, "overlap_s": 0.0,
    }
    for r in reports:
        agg["cycles"] += 1
        for k in ("wall_s", "attributed_s", "host_s", "device_s", "fsync_s"):
            agg[k] += r[k]
        for k in ("useful_rows", "padded_rows", "launches", "docs", "changes",
                  "h2d_bytes", "h2d_dense_bytes", "overlap_s"):
            agg[k] += r.get(k, 0)
        for k, v in r["stages"].items():
            agg["stages"][k] = agg["stages"].get(k, 0.0) + v
    return summarize(agg)


def summarize_flight_events(events: List[dict]) -> dict:
    """Rebuild the aggregate from flight-recorder ``drain.cycle_report``
    events (the offline ``perf-report`` path over a merged or raw flight
    dump). Event fields are the flat numeric form ``record`` emitted."""
    reports = []
    for e in events:
        if e.get("name") != "drain.cycle_report":
            continue
        f = e.get("fields") or {}

        def num(k, default=0.0):
            try:
                return float(f.get(k, default))
            except (TypeError, ValueError):
                return default

        stages = {
            k[len("stage_"):-2]: num(k)
            for k in f
            if k.startswith("stage_") and k.endswith("_s")
        }
        reports.append({
            "wall_s": num("wall_s"),
            "attributed_s": num("attributed_s"),
            "host_s": num("host_s"),
            "device_s": num("device_s"),
            "fsync_s": num("fsync_s"),
            "stages": stages,
            "useful_rows": int(num("useful_rows")),
            "padded_rows": int(num("padded_rows")),
            "launches": int(num("launches")),
            "docs": int(num("docs")),
            "changes": int(num("changes")),
            "h2d_bytes": int(num("h2d_bytes")),
            "h2d_dense_bytes": int(num("h2d_dense_bytes")),
            "overlap_s": num("overlap_s"),
        })
    out = summarize_reports(reports)
    out["source"] = "flight"
    return out


def render_text(summary: dict, top: Optional[int] = None) -> str:
    """The human perf-report: host-vs-device percentage breakdown, stage
    table, occupancy, and the expensive-docs tail."""
    lines = []
    frac = summary.get("attributed_frac", 0.0)
    lines.append(
        f"drain cycles: {summary.get('cycles', 0)}   "
        f"wall {summary.get('wall_s', 0.0):.4f}s   "
        f"attributed {100.0 * frac:.1f}%"
    )
    lines.append(
        f"split: host {summary.get('host_pct', 0.0):.1f}%  |  "
        f"device {summary.get('device_pct', 0.0):.1f}%  |  "
        f"fsync {summary.get('fsync_pct', 0.0):.1f}%"
    )
    stages = summary.get("stages") or {}
    # the host/device share of the measured drain wall itself, plus how
    # the host half split between the vectorized cross-doc staging
    # passes (host_pack/host_sort/host_splice) and the scalar per-doc
    # fallback (splice) — the ROADMAP item 4 acceptance line
    wall = summary.get("wall_s", 0.0)
    if wall > 0:
        hs = summary.get("host_s", 0.0)
        ds = summary.get("device_s", 0.0)
        vec = sum(
            stages.get(k, {}).get("seconds", 0.0)
            for k in ("host_pack", "host_sort", "host_splice")
        )
        sca = stages.get("splice", {}).get("seconds", 0.0)
        lines.append(
            f"share of wall: host {100.0 * hs / wall:.1f}%  |  "
            f"device {100.0 * ds / wall:.1f}%   "
            f"(host staging: vectorized {100.0 * vec / wall:.1f}%, "
            f"scalar {100.0 * sca / wall:.1f}%)"
        )
    ov = summary.get("overlap_s", 0.0)
    if ov:
        lines.append(
            f"pipeline overlap: {100.0 * summary.get('overlap_fraction', 0.0):.1f}% "
            f"of wall ({ov:.4f}s host work under in-flight launches)"
        )
    ec = summary.get("extract_cache") or {}
    if ec.get("cache_hit_ratio") is not None:
        lines.append(
            f"extract cache: {100.0 * ec['cache_hit_ratio']:.1f}% hits "
            f"({ec.get('hits', 0)}/{ec.get('hits', 0) + ec.get('misses', 0)})"
        )
    # h2d byte accounting: what the compressed staging actually moved vs
    # its dense equivalent (ops/compressed.py / merge.stage_cols_device)
    hb = summary.get("h2d_bytes", 0)
    if hb:
        ratio = summary.get("h2d_compress_ratio")
        lines.append(
            f"h2d: {hb} bytes staged "
            f"(dense equivalent {summary.get('h2d_dense_bytes', 0)}, "
            f"compress ratio {ratio if ratio is not None else 1.0}x)"
        )
    if stages:
        lines.append(f"  {'stage':<14} {'seconds':>10} {'% wall':>8}")
        for k, v in stages.items():
            lines.append(
                f"  {k:<14} {v['seconds']:>10.4f} {v['pct_of_wall']:>7.1f}%"
            )
        other = summary.get("host_other_s", 0.0)
        if other:
            wall = summary.get("wall_s", 0.0) or 1.0
            lines.append(
                f"  {'host_other':<14} {other:>10.4f} "
                f"{100.0 * other / wall:>7.1f}%"
            )
    occ = summary.get("occupancy")
    if occ is not None:
        lines.append(
            f"occupancy: {100.0 * occ:.1f}% "
            f"(useful {summary.get('useful_rows', 0)} rows, "
            f"padded {summary.get('padded_rows', 0)} rows)"
        )
    if summary.get("docs_per_launch") is not None:
        lines.append(
            f"launches: {summary.get('launches', 0)} "
            f"({summary['docs_per_launch']} docs/launch)"
        )
    for key, label in (("drain_cycle_seconds", "drain cycle"),
                       ("queue_wait_seconds", "queue wait")):
        q = summary.get(key)
        if q and any(q.values()):
            lines.append(
                f"{label}: p50 {q.get('p50', 0.0):.6f}s  "
                f"p95 {q.get('p95', 0.0):.6f}s  p99 {q.get('p99', 0.0):.6f}s"
            )
    td = summary.get("top_docs") or []
    if td:
        lines.append("top docs by attributed seconds:")
        for e in td[: top or len(td)]:
            lines.append(f"  {e['doc']:<32} {e['seconds']:.4f}s")
    he = (summary.get("heat") or {}).get("entries") or []
    if he:
        lines.append("doc heat (decayed per-second rates):")
        for e in he[: top or len(he)]:
            rates = "  ".join(
                f"{k} {v:.2f}/s"
                for k, v in sorted((e.get("rates") or {}).items())
            )
            lines.append(
                f"  {e['doc']:<32} rank {e.get('rank', 0.0):.2f}  {rates}"
            )
    jp = summary.get("jax_profiler")
    if jp and jp.get("active"):
        lines.append(f"jax profiler capture ACTIVE -> {jp.get('dir')}")
    return "\n".join(lines) + "\n"


# -- jax.profiler capture (profileStart / profileStop RPCs) -------------------

_jax_trace = {"active": False, "dir": None}
_jax_lock = threading.Lock()


def jax_profile_start(directory: Optional[str] = None) -> dict:
    """Start a ``jax.profiler`` trace capture into ``directory`` (a
    fresh temp dir when omitted). Degrades cleanly — an unavailable or
    unsupported profiler backend answers ``{"ok": false, "reason": ...}``
    and counts ``device.profiler_unavailable{reason=}``, it never
    raises (the ``enable_mesh`` contract)."""
    with _jax_lock:
        if _jax_trace["active"]:
            return {"ok": False, "reason": "capture already active",
                    "dir": _jax_trace["dir"]}
        if directory is None:
            import tempfile

            directory = tempfile.mkdtemp(prefix="amtpu_jaxprof_")
        try:
            import jax

            jax.profiler.start_trace(directory)
        except Exception as e:  # noqa: BLE001 — degrade, never raise
            _obs.count("device.profiler_unavailable",
                       labels={"reason": type(e).__name__})
            _obs.event("device.profiler_error", op="start",
                       error=str(e)[:200])
            return {"ok": False, "reason": str(e)[:200]}
        _jax_trace.update(active=True, dir=directory)
        _obs.count("device.profiler_captures")
        return {"ok": True, "dir": directory}


def jax_profile_stop() -> dict:
    """Stop the active capture; the response names the trace directory
    (open with TensorBoard's profile plugin or xprof)."""
    with _jax_lock:
        if not _jax_trace["active"]:
            return {"ok": False, "reason": "no active capture"}
        d = _jax_trace["dir"]
        _jax_trace.update(active=False, dir=None)
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            _obs.count("device.profiler_unavailable",
                       labels={"reason": type(e).__name__})
            _obs.event("device.profiler_error", op="stop",
                       error=str(e)[:200])
            return {"ok": False, "reason": str(e)[:200], "dir": d}
        return {"ok": True, "dir": d}


def annotate(name: str):
    """A named ``jax.profiler.TraceAnnotation`` around a kernel-launch
    site while a capture is active; a free ``nullcontext`` otherwise
    (the common case costs one dict read)."""
    if not _jax_trace["active"]:
        return nullcontext()
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001
        return nullcontext()
