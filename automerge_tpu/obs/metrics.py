"""Labeled metrics: a thread-safe registry of counters, gauges and
log-bucketed histograms, with Prometheus text exposition.

The reference gets this for free from the ``tracing``/``metrics`` crate
ecosystem; this is the Python analogue sized for our needs:

* **Counter** — monotone, ``inc(n)``.
* **Gauge** — last-write-wins, ``set(v)`` / ``add(n)``.
* **Histogram** — log-bucketed (geometric grid, factor ``2**0.25`` ≈ 19%
  per bucket), exposing ``percentile(q)`` (p50/p95/p99 within one bucket
  width of the exact quantile) plus count/sum/min/max. Buckets are stored
  sparsely, so an instrument costs a handful of dict slots regardless of
  the value range.

Every instrument family supports labels (``registry.counter("sync.retry",
peer="a")``); distinct label sets per family are capped
(``max_label_sets``, default 128) — past the cap, new sets collapse into
a single ``{overflow="true"}`` child so a label drawn from an unbounded
domain can degrade the data but never the process.

All mutation happens under one registry ``RLock``; instruments are cheap
enough to sit on hot paths (one lock round-trip + a few dict ops).
"""

from __future__ import annotations

import math
import re
import threading
from typing import Dict, Iterable, List, Optional, Tuple

# geometric bucket grid: upper bound of bucket i is FACTOR**i. FACTOR =
# 2**0.25 puts ~19% relative width on every bucket — the error bound on
# percentile estimates. Indices clamp to ±_IDX_RANGE (≈1e-15..1e15 for
# seconds or bytes); <=0 observations take the dedicated zero bucket.
FACTOR = 2.0 ** 0.25
_LOG_FACTOR = math.log(FACTOR)
_IDX_RANGE = 200
_ZERO_IDX = -(_IDX_RANGE + 1)

_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_metric_name(name: str) -> str:
    """Dots (our namespace separator) and other invalid characters become
    underscores; a leading digit gets a leading underscore."""
    s = _NAME_SANITIZE.sub("_", name)
    if s and s[0].isdigit():
        s = "_" + s
    return s


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _unescape_label_value(v: str) -> str:
    out: List[str] = []
    i = 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            out.append({"n": "\n", '"': '"', "\\": "\\"}.get(nxt, "\\" + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _format_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{sanitize_metric_name(k)}="{_escape_label_value(v)}"'
        for k, v in labels
    )
    return "{" + inner + "}"


class _Instrument:
    __slots__ = ("family", "labels")

    def __init__(self, family: "_Family", labels: Tuple[Tuple[str, str], ...]):
        self.family = family
        self.labels = labels

    @property
    def _lock(self):
        return self.family.registry.lock


class Counter(_Instrument):
    __slots__ = ("value",)

    def __init__(self, family, labels):
        super().__init__(family, labels)
        self.value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._inc_locked(n)

    def _inc_locked(self, n: int = 1) -> None:
        self.value += n


class Gauge(_Instrument):
    __slots__ = ("value",)

    def __init__(self, family, labels):
        super().__init__(family, labels)
        self.value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self.value = v

    def add(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Histogram(_Instrument):
    __slots__ = ("n", "total", "vmin", "vmax", "buckets")

    def __init__(self, family, labels):
        super().__init__(family, labels)
        self.n = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf
        self.buckets: Dict[int, int] = {}

    def observe(self, v: float) -> None:
        with self._lock:
            self._observe_locked(v)

    def _observe_locked(self, v: float) -> None:
        self.n += 1
        self.total += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v
        if v <= 0.0:
            i = _ZERO_IDX
        else:
            i = math.ceil(math.log(v) / _LOG_FACTOR)
            if i < -_IDX_RANGE:
                i = -_IDX_RANGE
            elif i > _IDX_RANGE:
                i = _IDX_RANGE
        b = self.buckets
        b[i] = b.get(i, 0) + 1

    @staticmethod
    def bucket_bounds(i: int) -> Tuple[float, float]:
        """(exclusive lower, inclusive upper) value bound of bucket ``i``."""
        if i == _ZERO_IDX:
            return (0.0, 0.0)
        lo = 0.0 if i == -_IDX_RANGE else FACTOR ** (i - 1)
        return (lo, FACTOR ** i)

    def percentile(self, q: float) -> float:
        """Quantile estimate by linear interpolation inside the bucket the
        rank lands in; exact min/max clamp the tails. 0.0 when empty."""
        with self._lock:
            if self.n == 0:
                return 0.0
            target = q * self.n
            cum = 0
            for i in sorted(self.buckets):
                c = self.buckets[i]
                if cum + c >= target:
                    lo, hi = self.bucket_bounds(i)
                    frac = (target - cum) / c
                    val = lo + (hi - lo) * frac
                    return min(max(val, self.vmin), self.vmax)
                cum += c
            return self.vmax

    def summary(self) -> dict:
        with self._lock:
            n, total = self.n, self.total
            vmin = self.vmin if n else 0.0
            vmax = self.vmax if n else 0.0
        return {
            "count": n,
            "sum": total,
            "min": vmin,
            "max": vmax,
            "p50": self.percentile(0.50),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
        }

    def cumulative_buckets(self) -> List[Tuple[float, int]]:
        """[(le_bound, cumulative_count)] over the buckets actually hit —
        the sparse form Prometheus's cumulative ``_bucket`` series allows."""
        with self._lock:
            cum = 0
            out: List[Tuple[float, int]] = []
            for i in sorted(self.buckets):
                cum += self.buckets[i]
                out.append((self.bucket_bounds(i)[1], cum))
            return out


_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}
# the cardinality-cap catch-all child's label set
_OVERFLOW_LABELS = (("overflow", "true"),)


class _Family:
    """One metric name: a type, a help string, and children by label set."""

    __slots__ = ("registry", "name", "type", "help", "children")

    def __init__(self, registry, name: str, type_: str, help_: str = ""):
        self.registry = registry
        self.name = name
        self.type = type_
        self.help = help_
        self.children: Dict[Tuple[Tuple[str, str], ...], _Instrument] = {}

    def _child_locked(self, labels: Tuple[Tuple[str, str], ...]):
        child = self.children.get(labels)
        if child is None:
            if (
                labels
                and labels != _OVERFLOW_LABELS
                and len(self.children) >= self.registry.max_label_sets
            ):
                return self._child_locked(_OVERFLOW_LABELS)
            child = _TYPES[self.type](self, labels)
            self.children[labels] = child
        return child


class MetricsRegistry:
    """Thread-safe instrument store. One global instance lives in
    ``automerge_tpu.obs``; tests construct their own."""

    def __init__(self, max_label_sets: int = 128):
        self.lock = threading.RLock()
        self.max_label_sets = max_label_sets
        # keyed by (name, type): a counter and a span histogram may share a
        # base name (e.g. device.delta_resolve counts calls AND times them);
        # the Prometheus rendering disambiguates (_total vs _bucket/_sum)
        self._families: Dict[Tuple[str, str], _Family] = {}

    # -- instrument lookup (get-or-create) ----------------------------------

    def _family_locked(self, name: str, type_: str, help_: str) -> _Family:
        fam = self._families.get((name, type_))
        if fam is None:
            fam = _Family(self, name, type_, help_)
            self._families[(name, type_)] = fam
        return fam

    def _get_locked(self, name, type_, labels: dict, help_=""):
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        return self._family_locked(name, type_, help_)._child_locked(key)

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        with self.lock:
            return self._get_locked(name, "counter", labels, help)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        with self.lock:
            return self._get_locked(name, "gauge", labels, help)

    def histogram(self, name: str, help: str = "", **labels) -> Histogram:
        with self.lock:
            return self._get_locked(name, "histogram", labels, help)

    def families(self) -> List[Tuple[str, str]]:
        """Sorted (name, type) pairs of every registered family."""
        with self.lock:
            return sorted(self._families)

    # -- removal (per-doc label hygiene) -------------------------------------

    def remove_labels(self, name: str, labels: dict,
                      type_: Optional[str] = None) -> int:
        """Remove the child with exactly this label set from every
        family named ``name`` (optionally one type). Returns how many
        children were removed.

        The reason this exists: per-document gauges
        (``doc.journal_bytes{doc=...}`` and friends) are keyed by an
        unbounded domain, and a long-lived server that opens documents
        forever would otherwise fill each family's cardinality cap with
        dead label sets — at which point every NEW document collapses
        into ``{overflow="true"}`` and the admission signal the tiered
        store's policy feeds on goes dark. Removing the labels when a
        document closes or demotes to cold keeps the cap's slots
        circulating among live documents. (Counters stay monotone for
        scrapers; removal is meant for gauges/histograms whose subject
        no longer exists — removing a counter's child is allowed but
        resets that series.)"""
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        removed = 0
        with self.lock:
            for (fname, ftype), fam in self._families.items():
                if fname != name or (type_ is not None and ftype != type_):
                    continue
                if fam.children.pop(key, None) is not None:
                    removed += 1
        return removed

    def gauge_remove(self, name: str, **labels) -> bool:
        """Remove one gauge child (sugar over ``remove_labels``)."""
        return self.remove_labels(name, labels, type_="gauge") > 0

    def reset(self) -> None:
        with self.lock:
            self._families.clear()

    # -- exposition ---------------------------------------------------------

    def render_prometheus(self) -> str:
        """Prometheus text format v0.0.4. Counter families render with the
        conventional ``_total`` suffix; histograms render sparse cumulative
        ``_bucket`` series plus ``_sum``/``_count``."""
        with self.lock:
            lines: List[str] = []
            for key in sorted(self._families):
                fam = self._families[key]
                pname = sanitize_metric_name(fam.name)
                if fam.type == "counter":
                    pname += "_total"
                if fam.help:
                    lines.append(f"# HELP {pname} {fam.help}")
                lines.append(f"# TYPE {pname} {fam.type}")
                for labels in sorted(fam.children):
                    child = fam.children[labels]
                    ltxt = _format_labels(labels)
                    if fam.type in ("counter", "gauge"):
                        lines.append(f"{pname}{ltxt} {_fmt_num(child.value)}")
                    else:
                        for le, cum in child.cumulative_buckets():
                            le_labels = labels + (("le", _fmt_num(le)),)
                            lines.append(
                                f"{pname}_bucket{_format_labels(le_labels)} {cum}"
                            )
                        inf_labels = labels + (("le", "+Inf"),)
                        lines.append(
                            f"{pname}_bucket{_format_labels(inf_labels)} {child.n}"
                        )
                        lines.append(f"{pname}_sum{ltxt} {_fmt_num(child.total)}")
                        lines.append(f"{pname}_count{ltxt} {child.n}")
            return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> List[dict]:
        """JSON-friendly dump: one entry per instrument child."""
        with self.lock:
            out: List[dict] = []
            for key in sorted(self._families):
                fam = self._families[key]
                for labels in sorted(fam.children):
                    child = fam.children[labels]
                    entry = {"name": fam.name, "type": fam.type,
                             "labels": dict(labels)}
                    if fam.type == "histogram":
                        entry.update(child.summary())
                    else:
                        entry["value"] = child.value
                    out.append(entry)
            return out


def _fmt_num(v) -> str:
    if isinstance(v, float):
        if v == math.inf:
            return "+Inf"
        if v == -math.inf:
            return "-Inf"
        if v != v:
            return "NaN"
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(v)
    return str(v)


# -- parsing (round-trip validation + scrape-side tooling) -------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _parse_label_text(ltxt: Optional[str]) -> List[Tuple[str, str]]:
    """``{k="v",...}`` (or None) -> [(k, v)] with escapes undone."""
    labels: List[Tuple[str, str]] = []
    if ltxt:
        body = ltxt[1:-1]
        pos = 0
        while pos < len(body):
            lm = _LABEL_RE.match(body, pos)
            if lm is None:
                raise ValueError(f"unparseable labels: {ltxt!r}")
            labels.append((lm.group(1), _unescape_label_value(lm.group(2))))
            pos = lm.end()
            if pos < len(body) and body[pos] == ",":
                pos += 1
    return labels


def parse_prometheus(text: str) -> Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float]:
    """Parse Prometheus text exposition back into
    ``{(name, sorted_label_items): value}`` — the round-trip half used by
    tests and by clients scraping the RPC ``metrics`` method."""
    out: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"unparseable sample line: {line!r}")
        name, ltxt, vtxt = m.groups()
        labels = _parse_label_text(ltxt)
        value = math.inf if vtxt == "+Inf" else float(vtxt)
        out[(name, tuple(sorted(labels)))] = value
    return out


def merge_prometheus(bodies: Dict[str, str], label: str = "node") -> str:
    """Merge per-node Prometheus expositions into ONE family set.

    ``bodies`` maps a node name to that node's ``render_prometheus()``
    text. Every sample gains a ``node="<name>"`` label (hostile node
    names are escaped exactly like any label value; a pre-existing label
    of the same name is replaced — the scraper's identity wins), family
    ``# TYPE``/``# HELP`` lines are unioned (first declaration wins),
    and histogram ``_bucket``/``_sum``/``_count`` series stay grouped
    under their family. Sample values pass through verbatim, so the
    merge is lossless and re-parses with ``parse_prometheus``."""
    types: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    samples: Dict[str, List[str]] = {}
    for node in sorted(bodies):
        for line in bodies[node].splitlines():
            line = line.strip()
            if not line:
                continue
            if line.startswith("# TYPE ") or line.startswith("# HELP "):
                parts = line.split(None, 3)
                if len(parts) >= 4:
                    target = types if parts[1] == "TYPE" else helps
                    target.setdefault(parts[2], parts[3])
                continue
            if line.startswith("#"):
                continue
            m = _SAMPLE_RE.match(line)
            if m is None:
                raise ValueError(f"unparseable sample line: {line!r}")
            name, ltxt, vtxt = m.groups()
            labels = [
                (k, v) for k, v in _parse_label_text(ltxt) if k != label
            ] + [(label, str(node))]
            ltxt_out = _format_labels(tuple(sorted(labels)))
            samples.setdefault(name, []).append(f"{name}{ltxt_out} {vtxt}")

    lines: List[str] = []
    emitted = set()
    for fam in sorted(types):
        if fam in helps:
            lines.append(f"# HELP {fam} {helps[fam]}")
        lines.append(f"# TYPE {fam} {types[fam]}")
        # counters/gauges sample under the family name itself; histogram
        # families fan out into the three conventional series
        for sname in (fam, fam + "_bucket", fam + "_sum", fam + "_count"):
            for s in samples.get(sname, ()):
                lines.append(s)
            emitted.add(sname)
    # samples whose body carried no TYPE line still merge (sorted tail)
    for sname in sorted(samples):
        if sname not in emitted:
            lines.extend(samples[sname])
    return "\n".join(lines) + ("\n" if lines else "")
