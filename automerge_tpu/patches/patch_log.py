"""PatchLog: the live observer cursor feeding materialized views.

Reference surface: rust/automerge/src/patches/patch_log.rs — a PatchLog
with an active/inactive switch that every mutating path feeds, drained by
``make_patches``. This implementation records the *heads cursor* instead
of per-op events: draining diffs cursor→current through patches/diff.py.
That one design choice makes every mutation route uniform — per-op apply,
the native bulk rebuild (core/bulk_load.py), the device merge kernel, and
load all advance the same cursor — where an event log would need bespoke
instrumentation in each (and could not observe the batched paths at all).
The produced patches are identical to the reference's collapsed event
stream: applying them to the before-state materializes the after-state
(tests/test_patches.py, tests/test_patch_log.py).

When inactive, draining is a no-op and nothing is computed — the hot
paths pay nothing (reference: patch_log.rs:105-152 active/inactive).
"""

from __future__ import annotations

from typing import Callable, List, Optional

from .diff import diff
from .patch import Patch


class PatchLog:
    __slots__ = ("active", "_cursor", "text_rep")

    def __init__(self, active: bool = True, text_rep: str = "string"):
        self.active = active
        self._cursor: Optional[List[bytes]] = None  # None = materialize all
        self.text_rep = text_rep

    def set_active(self, active: bool) -> None:
        self.active = active

    def is_active(self) -> bool:
        return self.active

    def reset(self, doc) -> None:
        """Move the cursor to the document's current heads."""
        self._cursor = doc.get_heads()

    def make_patches(self, doc) -> List[Patch]:
        """Drain: patches covering everything since the cursor (or the whole
        current state when the cursor was never set — the load /
        current_state case, reference automerge/current_state.rs)."""
        if not self.active:
            self._cursor = doc.get_heads()
            return []
        before = self._cursor if self._cursor is not None else []
        after = doc.get_heads()
        patches = diff(doc, before, after)
        self._cursor = after
        return patches


PatchCallback = Callable[[List[Patch]], None]
