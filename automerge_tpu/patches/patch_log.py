"""PatchLog: the live observer cursor feeding materialized views.

Reference surface: rust/automerge/src/patches/patch_log.rs — a PatchLog
with an active/inactive switch that every mutating path feeds, drained by
``make_patches``. This implementation records the *heads cursor* instead
of per-op events: draining diffs cursor→current through patches/diff.py.
That one design choice makes every mutation route uniform — per-op apply,
the native bulk rebuild (core/bulk_load.py), the device merge kernel, and
load all advance the same cursor — where an event log would need bespoke
instrumentation in each (and could not observe the batched paths at all).
The produced patches are identical to the reference's collapsed event
stream: applying them to the before-state materializes the after-state
(tests/test_patches.py, tests/test_patch_log.py).

Drain cost matches the reference's O(ops applied) event log: the cursor
also records the history length and the cursor CLOCK, so a drain diffs
only the runs touched by the changes appended since (diff_incremental) and
builds the after-clock by extending the cached cursor clock with those
changes — no ancestor traversal, no whole-document walk. The full walk
remains the fallback (first drain, or when the fast path declines).

When inactive, draining is a no-op and nothing is computed — the hot
paths pay nothing (reference: patch_log.rs:105-152 active/inactive).
"""

from __future__ import annotations

from typing import Callable, List, Optional

from ..core.clock import ClockData
from .diff import diff, diff_incremental
from .patch import Patch


class PatchLog:
    __slots__ = ("active", "_cursor", "text_rep", "_cursor_len", "_cursor_clock")

    def __init__(self, active: bool = True, text_rep: str = "string"):
        self.active = active
        self._cursor: Optional[List[bytes]] = None  # None = materialize all
        self._cursor_len: Optional[int] = None  # history length at cursor
        self._cursor_clock = None  # Clock at cursor (fast-drain cache)
        self.text_rep = text_rep

    def set_active(self, active: bool) -> None:
        self.active = active

    def is_active(self) -> bool:
        return self.active

    def _advance(self, doc, heads, clock) -> None:
        self._cursor = heads
        self._cursor_len = len(doc.history)
        self._cursor_clock = clock

    def reset(self, doc) -> None:
        """Move the cursor to the document's current heads."""
        heads = doc.get_heads()
        self._advance(doc, heads, doc.clock_at(heads))

    def make_patches(self, doc) -> List[Patch]:
        """Drain: patches covering everything since the cursor (or the whole
        current state when the cursor was never set — the load /
        current_state case, reference automerge/current_state.rs).

        Runs under the document's text encoding: patch indices count in
        its width unit."""
        from ..types import using_text_encoding

        with using_text_encoding(getattr(doc, "text_encoding", None)):
            return self._make_patches(doc)

    def _make_patches(self, doc) -> List[Patch]:
        after = doc.get_heads()
        if not self.active:
            self._advance(doc, after, None)
            return []
        before = self._cursor
        if (
            before is not None
            and self._cursor_len is not None
            and self._cursor_clock is not None
        ):
            new = doc.history[self._cursor_len:]
            if not new and before == after:
                return []
            # after-clock = cursor clock + the appended changes' own actor
            # data (their other ancestors are all at-or-before the cursor;
            # AppliedChange carries the translated actor index)
            after_clock = self._cursor_clock.copy()
            for a in new:
                after_clock.include(
                    a.actor_idx, ClockData(a.stored.max_op, a.stored.seq)
                )
            patches = diff_incremental(
                doc, self._cursor_clock, after_clock, new
            )
            if patches is None:
                patches = diff(doc, before, after)
                after_clock = doc.clock_at(after)
            self._advance(doc, after, after_clock)
            return patches
        patches = diff(doc, before if before is not None else [], after)
        self._advance(doc, after, doc.clock_at(after))
        return patches


PatchCallback = Callable[[List[Patch]], None]
