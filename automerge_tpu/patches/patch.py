"""Patch types: path-qualified descriptions of document mutations.

Mirrors the reference's patch surface (reference:
rust/automerge/src/patches/patch.rs): a ``Patch`` names the object it
touches, the path from the root to that object, and a ``PatchAction``.
Applying a diff's patches in order to the materialized ``before`` state
yields the ``after`` state (tests/test_patches.py holds this invariant).

Design note: patch values for newly-visible objects are fully hydrated
subtrees rather than the reference's create-empty-then-fill event stream —
one patch per structural change keeps consumers (and the device diff
kernel planned for ops/) simpler; the applied result is identical.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union

# path element: (object exid, key-or-index within it)
PathElem = Tuple[str, Union[str, int]]


@dataclass
class PutMap:
    key: str
    value: object
    conflict: bool = False


@dataclass
class PutSeq:
    index: int
    value: object
    conflict: bool = False


@dataclass
class Insert:
    index: int
    values: List[object] = field(default_factory=list)


@dataclass
class SpliceText:
    index: int
    value: str = ""


@dataclass
class DeleteMap:
    key: str


@dataclass
class DeleteSeq:
    index: int
    length: int = 1


@dataclass
class IncrementPatch:
    prop: Union[str, int]
    value: int


@dataclass
class MarkPatch:
    marks: List[object] = field(default_factory=list)


@dataclass
class FlagConflict:
    prop: Union[str, int]


PatchAction = Union[
    PutMap, PutSeq, Insert, SpliceText, DeleteMap, DeleteSeq,
    IncrementPatch, MarkPatch, FlagConflict,
]


@dataclass
class Patch:
    obj: str
    path: List[PathElem]
    action: PatchAction


def apply_patches(root, patches: List[Patch]):
    """Apply ``patches`` to a materialized tree (dicts / lists / strings).

    The reference's hydrate::Value::apply_patches equivalent
    (reference: rust/automerge/src/hydrate.rs:18-50). Returns the updated
    tree (strings are immutable, so text containers are rebuilt in place
    within their parent; pass and reassign the root).
    """
    for p in patches:
        root = _apply_one(root, p)
    return root


def _apply_one(root, p: Patch):
    # navigate to the target container, tracking the parent of a text leaf
    if not p.path:
        res = _apply_action(root, p.action, _Setter(None, None, lambda v: v))
        return res if res is not None else root

    node = root
    trail = []  # (container, key) pairs
    for _, key in p.path:
        trail.append((node, key))
        node = node[key]

    parent, last_key = trail[-1]

    def replace(v):
        parent[last_key] = v
        return root

    return _apply_action(node, p.action, _Setter(parent, last_key, replace)) or root


class _Setter:
    """How to write back a rebuilt (immutable) container, e.g. a str."""

    def __init__(self, parent, key, replace):
        self.parent = parent
        self.key = key
        self.replace = replace


def _apply_action(node, action, setter):
    if isinstance(action, PutMap):
        node[action.key] = action.value
    elif isinstance(action, DeleteMap):
        node.pop(action.key, None)
    elif isinstance(action, PutSeq):
        node[action.index] = action.value
    elif isinstance(action, Insert):
        node[action.index : action.index] = list(action.values)
    elif isinstance(action, DeleteSeq):
        if isinstance(node, str):
            return setter.replace(
                node[: action.index] + node[action.index + action.length :]
            )
        del node[action.index : action.index + action.length]
    elif isinstance(action, SpliceText):
        if isinstance(node, str):
            return setter.replace(
                node[: action.index] + action.value + node[action.index :]
            )
        node[action.index : action.index] = list(action.value)
    elif isinstance(action, IncrementPatch):
        node[action.prop] = node[action.prop] + action.value
    elif isinstance(action, (MarkPatch, FlagConflict)):
        pass  # no structural effect on plain materialized values
    else:
        raise TypeError(f"unknown patch action {action!r}")
    return None
