"""Diff: patches that transform the document state at one set of heads
into the state at another.

Semantics mirror the reference (reference: rust/automerge/src/automerge/
diff.rs log_diff): for every key pick the winning op at each clock and
emit New / Delete / Update / Increment patches; sequences walk elements in
document order with indices tracked against the evolving (before→after)
state so patches apply cleanly in order.

Host implementation over the op store; the per-key winner-at-clock
comparison is the same computation the device kernel performs with clock
masks (``counter <= clock[actor]`` — vectorized Clock::covers), so a
device-resident diff for huge histories is a planned extension of
ops/merge.py rather than a redesign.
"""

from __future__ import annotations

from typing import List, Optional

from ..core.clock import Clock
from ..core.op_store import MapObject, Op, ROOT_OBJ, SeqObject
from ..types import ObjType, is_make_action
from .patch import (
    DeleteMap,
    DeleteSeq,
    FlagConflict,
    IncrementPatch,
    Insert,
    Patch,
    PutMap,
    PutSeq,
    SpliceText,
)


def diff(doc, before_heads: List[bytes], after_heads: List[bytes]) -> List[Patch]:
    """Patches turning the state at ``before_heads`` into ``after_heads``."""
    before = doc.clock_at(before_heads) if before_heads is not None else Clock()
    after = doc.clock_at(after_heads)
    patches: List[Patch] = []
    _diff_obj(doc, ROOT_OBJ, before, after, patches, path=[])
    return patches


def _winner(ops: List[Op], clock) -> Optional[Op]:
    vis = [o for o in ops if o.visible_at(clock)]
    return vis[-1] if vis else None


def _render(doc, op: Op, clock):
    """Patch value of a winning op: hydrated subtree / counter / scalar."""
    if is_make_action(op.action):
        return doc._hydrate(op.id, clock)
    if op.is_counter:
        return op.counter_value_at(clock)
    return op.value.to_py()


def _diff_obj(doc, obj_id, before, after, patches, path):
    info = doc.ops.get_obj(obj_id)
    exid = doc.export_id(obj_id)
    if isinstance(info.data, MapObject):
        _diff_map(doc, obj_id, exid, info.data, before, after, patches, path)
    elif info.data.obj_type == ObjType.TEXT:
        _diff_text(doc, obj_id, exid, info.data, before, after, patches, path)
    else:
        _diff_list(doc, obj_id, exid, info.data, before, after, patches, path)


def _diff_map(doc, obj_id, exid, data, before, after, patches, path):
    for key_idx in sorted(data.props, key=lambda k: doc.props.get(k)):
        run = data.props[key_idx]
        key = doc.props.get(key_idx)
        wb = _winner(run, before)
        wa = _winner(run, after)
        if wa is None:
            if wb is not None:
                patches.append(Patch(exid, list(path), DeleteMap(key)))
            continue
        conflict = sum(o.visible_at(after) for o in run) > 1
        if wb is None or wb.id != wa.id:
            patches.append(
                Patch(exid, list(path), PutMap(key, _render(doc, wa, after), conflict))
            )
        elif wa.is_counter:
            delta = wa.counter_value_at(after) - wb.counter_value_at(before)
            if delta:
                patches.append(Patch(exid, list(path), IncrementPatch(key, delta)))
        elif conflict and sum(o.visible_at(before) for o in run) <= 1:
            patches.append(Patch(exid, list(path), FlagConflict(key)))
        if is_make_action(wa.action) and wb is not None and wb.id == wa.id:
            _diff_obj(doc, wa.id, before, after, patches, path + [(exid, key)])


def _diff_list(doc, obj_id, exid, data, before, after, patches, path):
    idx = 0
    pending_ins = None  # (index, [values])
    for el in data.elements():
        wb = el.winner(before)
        wa = el.winner(after)
        if wa is None and wb is None:
            continue
        if wa is not None and wb is None:
            if pending_ins is None:
                pending_ins = (idx, [])
            pending_ins[1].append(_render(doc, wa, after))
            idx += 1
            continue
        if pending_ins is not None:
            patches.append(Patch(exid, list(path), Insert(*pending_ins)))
            pending_ins = None
        if wa is None:
            # element disappeared: coalesce with a preceding delete
            last = patches[-1] if patches else None
            if (
                last is not None
                and last.obj == exid
                and isinstance(last.action, DeleteSeq)
                and last.action.index == idx
            ):
                last.action.length += 1
            else:
                patches.append(Patch(exid, list(path), DeleteSeq(idx)))
            continue
        conflict = len(el.visible_ops(after)) > 1
        if wb.id != wa.id:
            patches.append(
                Patch(
                    exid,
                    list(path),
                    PutSeq(idx, _render(doc, wa, after), conflict),
                )
            )
        elif wa.is_counter:
            delta = wa.counter_value_at(after) - wb.counter_value_at(before)
            if delta:
                patches.append(Patch(exid, list(path), IncrementPatch(idx, delta)))
        elif conflict and len(el.visible_ops(before)) <= 1:
            patches.append(Patch(exid, list(path), FlagConflict(idx)))
        if is_make_action(wa.action) and wb.id == wa.id:
            _diff_obj(doc, wa.id, before, after, patches, path + [(exid, idx)])
        idx += 1
    if pending_ins is not None:
        patches.append(Patch(exid, list(path), Insert(*pending_ins)))


def _diff_text(doc, obj_id, exid, data, before, after, patches, path):
    idx = 0
    pending = None  # (index, str) for inserts
    for el in data.elements():
        wb = el.winner(before)
        wa = el.winner(after)
        if wa is None and wb is None:
            continue
        sa = _char(wa) if wa is not None else None
        sb = _char(wb) if wb is not None else None
        if wa is not None and wb is None:
            if pending is None:
                pending = [idx, ""]
            pending[1] += sa
            idx += len(sa)
            continue
        if pending is not None:
            patches.append(Patch(exid, list(path), SpliceText(pending[0], pending[1])))
            pending = None
        if wa is None:
            last = patches[-1] if patches else None
            if (
                last is not None
                and last.obj == exid
                and isinstance(last.action, DeleteSeq)
                and last.action.index == idx
            ):
                last.action.length += len(sb)
            else:
                patches.append(Patch(exid, list(path), DeleteSeq(idx, len(sb))))
            continue
        if wb.id != wa.id and (sa != sb):
            patches.append(Patch(exid, list(path), DeleteSeq(idx, len(sb))))
            patches.append(Patch(exid, list(path), SpliceText(idx, sa)))
        idx += len(sa)
    if pending is not None:
        patches.append(Patch(exid, list(path), SpliceText(pending[0], pending[1])))


def _char(op: Op) -> str:
    return op.value.value if op.value.tag == "str" else "￼"
