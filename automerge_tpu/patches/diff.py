"""Diff: patches that transform the document state at one set of heads
into the state at another.

Semantics mirror the reference (reference: rust/automerge/src/automerge/
diff.rs log_diff): for every key pick the winning op at each clock and
emit New / Delete / Update / Increment patches; sequences walk elements in
document order with indices tracked against the evolving (before→after)
state so patches apply cleanly in order.

Host implementation over the op store; the per-key winner-at-clock
comparison is the same computation the device kernel performs with clock
masks (``counter <= clock[actor]`` — vectorized Clock::covers), so a
device-resident diff for huge histories is a planned extension of
ops/merge.py rather than a redesign.
"""

from __future__ import annotations

from typing import List, Optional

from ..core.clock import Clock
from ..core.op_store import MapObject, Op, ROOT_OBJ, SeqObject
from ..types import ObjType, is_make_action
from .patch import (
    DeleteMap,
    DeleteSeq,
    FlagConflict,
    IncrementPatch,
    Insert,
    MarkPatch,
    Patch,
    PutMap,
    PutSeq,
    SpliceText,
)


def diff(doc, before_heads: List[bytes], after_heads: List[bytes]) -> List[Patch]:
    """Patches turning the state at ``before_heads`` into ``after_heads``."""
    before = doc.clock_at(before_heads) if before_heads is not None else Clock()
    after = doc.clock_at(after_heads)
    patches: List[Patch] = []
    _diff_obj(doc, ROOT_OBJ, before, after, patches, path=[])
    return patches


def _winner(ops: List[Op], clock) -> Optional[Op]:
    vis = [o for o in ops if o.visible_at(clock)]
    return vis[-1] if vis else None


def _render(doc, op: Op, clock):
    """Patch value of a winning op: hydrated subtree / counter / scalar."""
    if is_make_action(op.action):
        return doc._hydrate(op.id, clock)
    if op.is_counter:
        return op.counter_value_at(clock)
    return op.value.to_py()


def _diff_obj(doc, obj_id, before, after, patches, path):
    info = doc.ops.get_obj(obj_id)
    exid = doc.export_id(obj_id)
    if isinstance(info.data, MapObject):
        _diff_map(doc, obj_id, exid, info.data, before, after, patches, path)
    elif info.data.obj_type == ObjType.TEXT:
        _diff_text(doc, obj_id, exid, info.data, before, after, patches, path)
        _diff_marks(doc, exid, info.data, before, after, patches, path)
    else:
        _diff_list(doc, obj_id, exid, info.data, before, after, patches, path)
        _diff_marks(doc, exid, info.data, before, after, patches, path)


def _diff_marks(doc, exid, data, before, after, patches, path):
    """Emit a MarkPatch when the resolved mark spans differ between the two
    clocks (reference: diff.rs MarkDiff). Replace-all semantics: the patch
    carries the FULL after-state span set for the object; consumers
    replace its marks wholesale. Span positions shift with plain text
    edits inside marked ranges, so this compares resolved spans, not mark
    ops. Skipped wholesale for never-marked objects (block mark counts)."""
    if not any(b.marks for b in data.blocks):
        return
    mb = doc.marks(exid, clock=before)
    ma = doc.marks(exid, clock=after)

    def key(ms):
        return [(m.start, m.end, m.name, m.value) for m in ms]

    if key(mb) != key(ma):
        patches.append(Patch(exid, list(path), MarkPatch(list(ma))))


def _diff_map_key(doc, exid, key, run, before, after, patches, path):
    """Diff ONE map run; returns the winner when both clocks agree on a
    make op (the caller may recurse into it)."""
    wb = _winner(run, before)
    wa = _winner(run, after)
    if wa is None:
        if wb is not None:
            patches.append(Patch(exid, list(path), DeleteMap(key)))
        return None
    conflict = sum(o.visible_at(after) for o in run) > 1
    if wb is None or wb.id != wa.id:
        patches.append(
            Patch(exid, list(path), PutMap(key, _render(doc, wa, after), conflict))
        )
    elif wa.is_counter:
        delta = wa.counter_value_at(after) - wb.counter_value_at(before)
        if delta:
            patches.append(Patch(exid, list(path), IncrementPatch(key, delta)))
    elif conflict and sum(o.visible_at(before) for o in run) <= 1:
        patches.append(Patch(exid, list(path), FlagConflict(key)))
    if is_make_action(wa.action) and wb is not None and wb.id == wa.id:
        return wa
    return None


def _diff_map(doc, obj_id, exid, data, before, after, patches, path):
    for key_idx in sorted(data.props, key=lambda k: doc.props.get(k)):
        run = data.props[key_idx]
        key = doc.props.get(key_idx)
        wa = _diff_map_key(doc, exid, key, run, before, after, patches, path)
        if wa is not None:
            _diff_obj(doc, wa.id, before, after, patches, path + [(exid, key)])


class _ListEmitter:
    """Per-element list-diff state machine, shared by the full walk (running
    index) and the incremental drain (computed index): emits
    Insert/Delete/Put/Increment/FlagConflict with insert/delete coalescing.

    ``visit`` takes ``idx`` = the element's hybrid position (count of
    after-visible elements before it — identical to the full walk's running
    counter) and returns the winner to recurse into, if any."""

    def __init__(self, doc, exid, path, before, after, patches):
        self.doc, self.exid, self.path = doc, exid, list(path)
        self.before, self.after, self.patches = before, after, patches
        self.pending_ins = None  # (index, [values])

    def _flush(self):
        if self.pending_ins is not None:
            self.patches.append(
                Patch(self.exid, list(self.path), Insert(*self.pending_ins))
            )
            self.pending_ins = None

    def visit(self, el, wb, wa, idx):
        doc, exid, path = self.doc, self.exid, self.path
        before, after, patches = self.before, self.after, self.patches
        if wa is not None and wb is None:
            if (
                self.pending_ins is None
                or self.pending_ins[0] + len(self.pending_ins[1]) != idx
            ):
                self._flush()
                self.pending_ins = (idx, [])
            self.pending_ins[1].append(_render(doc, wa, after))
            return None
        self._flush()
        if wa is None:
            # element disappeared: coalesce with a preceding delete
            last = patches[-1] if patches else None
            if (
                last is not None
                and last.obj == exid
                and isinstance(last.action, DeleteSeq)
                and last.action.index == idx
            ):
                last.action.length += 1
            else:
                patches.append(Patch(exid, list(path), DeleteSeq(idx)))
            return None
        conflict = len(el.visible_ops(after)) > 1
        if wb.id != wa.id:
            patches.append(
                Patch(exid, list(path), PutSeq(idx, _render(doc, wa, after), conflict))
            )
        elif wa.is_counter:
            delta = wa.counter_value_at(after) - wb.counter_value_at(before)
            if delta:
                patches.append(Patch(exid, list(path), IncrementPatch(idx, delta)))
        elif conflict and len(el.visible_ops(before)) <= 1:
            patches.append(Patch(exid, list(path), FlagConflict(idx)))
        if is_make_action(wa.action) and wb.id == wa.id:
            return wa
        return None


class _TextEmitter:
    """Per-element text-diff state machine (splice/delete coalescing);
    ``idx`` is the element's hybrid text position (sum of after-visible
    character lengths before it)."""

    def __init__(self, exid, path, before, after, patches):
        self.exid, self.path = exid, list(path)
        self.before, self.after, self.patches = before, after, patches
        self.pending = None  # [index, str]

    def _flush(self):
        if self.pending is not None:
            self.patches.append(
                Patch(self.exid, list(self.path), SpliceText(*self.pending))
            )
            self.pending = None

    def visit(self, el, wb, wa, idx):
        exid, path, patches = self.exid, self.path, self.patches
        sa = _char(wa) if wa is not None else None
        sb = _char(wb) if wb is not None else None
        if wa is not None and wb is None:
            if self.pending is None or self.pending[0] + len(self.pending[1]) != idx:
                self._flush()
                self.pending = [idx, ""]
            self.pending[1] += sa
            return None
        self._flush()
        if wa is None:
            last = patches[-1] if patches else None
            if (
                last is not None
                and last.obj == exid
                and isinstance(last.action, DeleteSeq)
                and last.action.index == idx
            ):
                last.action.length += len(sb)
            else:
                patches.append(Patch(exid, list(path), DeleteSeq(idx, len(sb))))
            return None
        if wb.id != wa.id and (sa != sb):
            patches.append(Patch(exid, list(path), DeleteSeq(idx, len(sb))))
            patches.append(Patch(exid, list(path), SpliceText(idx, sa)))
        return None


def _diff_list(doc, obj_id, exid, data, before, after, patches, path):
    em = _ListEmitter(doc, exid, path, before, after, patches)
    idx = 0
    for el in data.elements():
        wb = el.winner(before)
        wa = el.winner(after)
        if wa is None and wb is None:
            continue
        w = em.visit(el, wb, wa, idx)
        if w is not None:
            _diff_obj(doc, w.id, before, after, patches, path + [(exid, idx)])
        if wa is not None:
            idx += 1
    em._flush()


def _diff_text(doc, obj_id, exid, data, before, after, patches, path):
    em = _TextEmitter(exid, path, before, after, patches)
    idx = 0
    for el in data.elements():
        wb = el.winner(before)
        wa = el.winner(after)
        if wa is None and wb is None:
            continue
        em.visit(el, wb, wa, idx)
        if wa is not None:
            idx += len(_char(wa))
    em._flush()


def _char(op: Op) -> str:
    return op.value.value if op.value.tag == "str" else "￼"


# -- incremental drain --------------------------------------------------------
#
# The reference's PatchLog costs O(ops applied) because it records events at
# apply time (reference: patches/patch_log.rs:43-103). The heads-cursor
# design here recovers the same asymptotics at DRAIN time instead: the new
# changes since the cursor name exactly the (object, key/element) runs that
# can have changed, each touched run re-diffs in isolation, and sequence
# positions resolve through the block order-statistics index (O(sqrt n))
# rather than a whole-object walk. Anything the fast path cannot prove it
# handles returns None and the caller falls back to the full walk.


def diff_incremental(doc, before, after, new_applied) -> Optional[List[Patch]]:
    """Patches for ``before -> after`` (clocks) derived from the
    ``new_applied`` AppliedChanges only; None when a precondition fails
    (caller uses the full diff).

    Cost: O(new ops) to collect touched runs + O(block) per touched
    sequence element (positions resolve through a per-object block prefix
    sum) + O(run) per touched run — independent of document size.

    Precondition (checked): the op store reflects exactly the ``after``
    clock — a live transaction's eagerly-applied ops would skew
    current-state positions, so callers must drain only at commit
    boundaries (PatchLog falls back to the clock-filtered full walk
    otherwise)."""
    from ..types import get_text_encoding, is_head, is_root

    live = doc._live_transaction()
    if live is not None and live.pending_ops():
        return None

    # 1. touched (object -> keys/elements) from the new changes' ops,
    #    using each change's stored actor translation table
    from ..types import Action

    touched_map: dict = {}  # obj_id -> set of prop names
    touched_seq: dict = {}  # obj_id -> set of element OpIds
    touched_mark_ops: set = set()  # objects with new mark/unmark ops
    for applied in new_applied:
        ch = applied.stored
        amap = applied.actor_map
        author = applied.actor_idx
        for i, cop in enumerate(ch.ops):
            obj = (
                ROOT_OBJ
                if is_root(cop.obj)
                else (cop.obj[0], amap[cop.obj[1]])
            )
            if cop.key.prop is not None:
                touched_map.setdefault(obj, set()).add(cop.key.prop)
                continue
            if cop.action == Action.MARK:
                touched_mark_ops.add(obj)
            if cop.insert:
                elem = (ch.start_op + i, author)
            else:
                e = cop.key.elem
                if is_head(e):
                    return None  # malformed: non-insert at HEAD
                elem = (e[0], amap[e[1]])
            touched_seq.setdefault(obj, set()).add(elem)

    # 2. eligibility: content patches apply to X only when every ancestor
    #    link's winner is the same make op at both clocks (the full walk's
    #    recursion condition); otherwise an ancestor patch re-renders X
    eligible: dict = {ROOT_OBJ: True}

    def obj_eligible(obj_id) -> bool:
        cached = eligible.get(obj_id)
        if cached is not None:
            return cached
        try:
            info = doc.ops.get_obj(obj_id)
        except Exception:
            eligible[obj_id] = False
            return False
        ok = obj_eligible(info.parent)
        if ok:
            pdata = doc.ops.get_obj(info.parent).data
            if info.parent_key is not None:
                run = pdata.props.get(info.parent_key)
                wb = _winner(run, before) if run else None
                wa = _winner(run, after) if run else None
            elif pdata.obj_type == ObjType.TEXT:
                # the full walk never recurses into objects nested in TEXT
                # (_TextEmitter yields no winners) — mirror that, or the
                # fast path would emit patches the fallback suppresses
                wb = wa = None
            else:
                el = pdata.by_id.get(info.parent_elem)
                wb = el.winner(before) if el is not None else None
                wa = el.winner(after) if el is not None else None
            ok = wb is not None and wa is not None and wb.id == wa.id == obj_id
        eligible[obj_id] = ok
        return ok

    # 3. path + depth per eligible object (parents first in output)
    def obj_path(obj_id):
        return list(reversed(doc.parents(doc.export_id(obj_id))))

    work = []
    for obj_id in set(touched_map) | set(touched_seq):
        if not obj_eligible(obj_id):
            continue
        path = obj_path(obj_id)
        work.append((len(path), doc.export_id(obj_id), obj_id, path))
    work.sort(key=lambda w: (w[0], w[1]))

    patches: List[Patch] = []
    for _, exid, obj_id, path in work:
        info = doc.ops.get_obj(obj_id)
        data = info.data
        if isinstance(data, MapObject):
            for key in sorted(touched_map.get(obj_id, ())):
                key_idx = doc.props.lookup(key)
                run = data.props.get(key_idx) if key_idx is not None else None
                if run is None:
                    return None  # op applied but run absent: fall back
                _diff_map_key(doc, exid, key, run, before, after, patches, path)
            continue
        is_text = data.obj_type == ObjType.TEXT
        if is_text and get_text_encoding() != "unicode":
            return None  # width units diverge from the walk's len() accounting
        # touched elements in document order: (block position, slot in block)
        elems = []
        for eid in touched_seq.get(obj_id, ()):
            el = data.by_id.get(eid)
            if el is None:
                return None
            elems.append(el)
        # per-object block position + visible-width prefix, scanning only
        # until every touched element's block has been seen — drain cost
        # is bounded by the FURTHEST touched block, not the object size
        need = {id(el.block) for el in elems}
        if None in (el.block for el in elems):
            return None
        block_pos = {}
        prefix = {}
        acc = 0
        for i, b in enumerate(data.blocks):
            bid = id(b)
            block_pos[bid] = i
            prefix[i] = acc
            acc += b.width if is_text else b.vis
            need.discard(bid)
            if not need:
                break

        def doc_order(el):
            b = el.block
            if b is None or id(b) not in block_pos:
                return None
            return (block_pos[id(b)], b.els.index(el))

        def pos_of(el):
            b = el.block
            at = prefix[block_pos[id(b)]]
            for e in b.els:
                if e is el:
                    return at
                w = e.winner()
                if w is not None:
                    at += w.text_width() if is_text else 1
            return None

        keyed = []
        for el in elems:
            k = doc_order(el)
            if k is None:
                return None
            keyed.append((k, el))
        keyed.sort(key=lambda t: t[0])
        em = (
            _TextEmitter(exid, path, before, after, patches)
            if is_text
            else _ListEmitter(doc, exid, path, before, after, patches)
        )
        min_idx = None
        for _, el in keyed:
            wb = el.winner(before)
            wa = el.winner(after)
            if wa is None and wb is None:
                continue
            idx = pos_of(el)
            if idx is None:
                return None
            if min_idx is None or idx < min_idx:
                min_idx = idx
            # NOTE: unlike the full walk, do NOT recurse into an unchanged
            # child winner — a touched child diffs via its own entry, and
            # recursing here would emit its patches twice
            em.visit(el, wb, wa, idx)
        em._flush()
        # Mark spans can only change when (a) mark/unmark ops touched the
        # object, or (b) an edit landed at or before the marked region
        # (positions shift; expand grows at boundaries). The bound — the
        # width prefix through one block past the last block holding mark
        # ops — costs O(#blocks); edits past it skip the O(object) span
        # resolution, preserving the drain's O(edit) asymptotics.
        if obj_id in touched_mark_ops:
            _diff_marks(doc, exid, data, before, after, patches, path)
        else:
            blocks = data.blocks
            last_marked = -1
            for bi, b in enumerate(blocks):
                if b.marks:
                    last_marked = bi
            if last_marked >= 0 and min_idx is not None:
                # width prefix through one block past the last marked one
                # (the slack covers expand-at-boundary growth)
                upto = min(last_marked + 1, len(blocks) - 1)
                bound = sum(
                    blocks[bi].width if is_text else blocks[bi].vis
                    for bi in range(upto + 1)
                )
                if min_idx <= bound:
                    _diff_marks(doc, exid, data, before, after, patches, path)
    return patches
