from .diff import diff
from .patch import (
    DeleteMap,
    DeleteSeq,
    FlagConflict,
    IncrementPatch,
    Insert,
    MarkPatch,
    Patch,
    PutMap,
    PutSeq,
    SpliceText,
    apply_patches,
)

__all__ = [
    "Patch",
    "PutMap",
    "PutSeq",
    "Insert",
    "SpliceText",
    "DeleteMap",
    "DeleteSeq",
    "IncrementPatch",
    "MarkPatch",
    "FlagConflict",
    "apply_patches",
    "diff",
]
