"""Tiered document store: hot / warm / cold residency with policy-driven
demotion and lazy, single-flight hydration. See docstore.py."""

from .docstore import ColdDocRef, DocStore, StoreBackpressure  # noqa: F401
from .policy import (  # noqa: F401
    TIER_COLD,
    TIER_HOT,
    TIER_WARM,
    TIERS,
    DocStats,
    StoreBudgets,
    current_rss_bytes,
    pick_demotions,
)
