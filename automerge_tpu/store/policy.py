"""Residency policy: budgets, cost signals and victim selection.

The tiered store's demotion decisions are pure functions over a
snapshot of per-document accounting (last access stamp, resident-byte
estimate, tier) plus the configured budgets — kept separate from the
``DocStore`` mechanics so the policy is unit-testable without opening a
single journal.

The shape follows SynchroStore's cost-based incremental compaction
(arXiv:2503.18688) and the Real-Time LSM-Tree HTAP tiering argument
(arXiv:2101.06801): write-hot documents stay fully (device-)resident,
read-mostly documents keep only the host op-store, and idle documents
collapse to their on-disk snapshot + journal tail. Victims are picked
least-recently-used first; the cost side shows up as (a) the
compact-on-demote gate (a journal smaller than
``cold_compact_min_bytes`` is cheaper to replay than to re-snapshot)
and (b) the resident-byte estimate that orders the warm set's pressure.

Budgets (all ``0`` = unbounded, the default — an unconfigured store is
pure bookkeeping and never demotes):

* ``hot_docs``   — max documents holding a device mirror
  (``AUTOMERGE_TPU_STORE_HOT_DOCS``).
* ``warm_bytes`` — max estimated host-resident bytes across live
  (hot + warm) documents (``AUTOMERGE_TPU_STORE_WARM_BYTES``).
* ``max_rss_bytes`` — hard process-RSS watermark: past it the store
  demotes LRU live documents to cold until the process is back under
  (or nothing demotable remains) (``AUTOMERGE_TPU_STORE_MAX_RSS``).
* ``idle_cold_s`` — optional age-based demotion: any live document
  idle longer than this goes cold regardless of budgets
  (``AUTOMERGE_TPU_STORE_IDLE_COLD_S``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

TIER_HOT = "hot"
TIER_WARM = "warm"
TIER_COLD = "cold"
TIERS = (TIER_HOT, TIER_WARM, TIER_COLD)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


@dataclass(frozen=True)
class StoreBudgets:
    """Residency budgets; 0 disables the corresponding bound."""

    hot_docs: int = 0
    warm_bytes: int = 0
    max_rss_bytes: int = 0
    idle_cold_s: float = 0.0
    # concurrent cold-open bound: past it, access answers a retriable
    # Backpressure instead of queueing unbounded hydration work
    max_hydrations: int = 4
    # background sweep cadence (idle/RSS pressure is time-driven, not
    # only admission-driven); 0 disables the thread
    evict_interval_s: float = 1.0
    # demote-to-cold compacts first ONLY when the journal is at least
    # this big — replaying a small tail on hydrate is cheaper than
    # re-snapshotting the document on every demotion
    cold_compact_min_bytes: int = 64 << 10
    # demotion floor: a document accessed within this window is never a
    # victim, whatever the budgets say. Keeps a doc that is mid-flight
    # between handle resolution and its mutation from being closed out
    # from under the request (the closed-instance guard makes that a
    # retriable error, not a loss — this floor makes it rare), and damps
    # hydrate/demote thrash under budgets tighter than the working set.
    min_idle_s: float = 0.1

    @classmethod
    def from_env(cls) -> "StoreBudgets":
        return cls(
            hot_docs=_env_int("AUTOMERGE_TPU_STORE_HOT_DOCS", 0),
            warm_bytes=_env_int("AUTOMERGE_TPU_STORE_WARM_BYTES", 0),
            max_rss_bytes=_env_int("AUTOMERGE_TPU_STORE_MAX_RSS", 0),
            idle_cold_s=_env_float("AUTOMERGE_TPU_STORE_IDLE_COLD_S", 0.0),
            max_hydrations=_env_int("AUTOMERGE_TPU_STORE_HYDRATIONS", 4),
            evict_interval_s=_env_float(
                "AUTOMERGE_TPU_STORE_EVICT_INTERVAL", 1.0),
            cold_compact_min_bytes=_env_int(
                "AUTOMERGE_TPU_STORE_COLD_COMPACT_MIN", 64 << 10),
            min_idle_s=_env_float("AUTOMERGE_TPU_STORE_MIN_IDLE", 0.1),
        )

    @property
    def active(self) -> bool:
        """True when any bound can actually force a demotion."""
        return bool(
            self.hot_docs or self.warm_bytes
            or self.max_rss_bytes or self.idle_cold_s
        )


@dataclass
class DocStats:
    """One document's policy-relevant accounting snapshot."""

    name: str
    tier: str
    last_access: float  # obs.now() stamp
    resident_bytes: int = 0

    def idle_s(self, now: float) -> float:
        return max(0.0, now - self.last_access)


@dataclass
class Demotion:
    name: str
    to: str  # TIER_WARM or TIER_COLD
    reason: str


def compact_on_demote(journal_bytes: int, has_run_image: bool,
                      history_len: int, budgets: StoreBudgets) -> bool:
    """Should a warm→cold demotion compact before closing?

    The cost side of the tiering model: a journal smaller than
    ``cold_compact_min_bytes`` is cheaper to replay on the next hydrate
    than to re-snapshot now — UNLESS the document has no run-coded image
    yet (legacy-format or absent snapshot), in which case one compaction
    here converts the cold copy to the run-coded format and every later
    hydration becomes decode-only. Write-hot docs therefore keep short
    tails; read-mostly docs converge to a pure image."""
    if journal_bytes >= budgets.cold_compact_min_bytes:
        return True
    from ..storage import runsnap

    return runsnap.enabled() and not has_run_image and history_len > 0


def device_resident_bytes(dev) -> int:
    """Device-path footprint of one resident ``DeviceDoc`` mirror, as
    the admission/demotion policy should see it: TRUE resident bytes —
    the compressed column image a drain actually ships plus the
    resolution readbacks — not the dense-equivalent array bytes the
    estimate used to report. Reads the owner-stamped cache
    (``DeviceDoc.resident_nbytes_estimate``): the evict sweeper runs
    off-thread, and computing the figure fresh would sync the log's
    compressed image under a concurrent append. With
    ``AUTOMERGE_TPU_COMPRESSED=0`` the two modes coincide."""
    try:
        return int(dev.resident_nbytes_estimate())
    except Exception:
        # a mirror mid-teardown (or a foreign duck-type): fall back to
        # whatever readback arrays are still reachable
        try:
            return sum(a.nbytes for a in dev.res.values())
        except Exception:
            return 0


def current_rss_bytes() -> int:
    """This process's current resident set size. Linux reads
    ``/proc/self/statm`` (current, not peak); elsewhere falls back to
    ``getrusage`` peak RSS — a watermark against the peak is still a
    watermark, just a sticky one."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * (os.sysconf("SC_PAGE_SIZE"))
    except Exception:
        import resource
        import sys

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # ru_maxrss is KiB on Linux but BYTES on macOS — a 1024x
        # misread here would make the watermark pass see permanent
        # excess and demote the whole working set every sweep
        return peak if sys.platform == "darwin" else peak * 1024


def pick_demotions(
    stats: List[DocStats],
    budgets: StoreBudgets,
    *,
    now: float,
    rss_bytes: Optional[int] = None,
) -> List[Demotion]:
    """The policy: which documents leave their tier, and why.

    Pure over its inputs. Order of enforcement (each pass works on the
    state the previous pass left behind):

    1. ``idle_cold_s`` — age out idle live docs to cold.
    2. ``hot_docs``    — LRU hot docs drop their device mirror (→ warm).
    3. ``warm_bytes``  — LRU live docs go cold until the estimated
       host-resident total fits.
    4. ``max_rss_bytes`` — hard watermark: LRU live docs go cold until
       the measured RSS is projected back under (resident-byte
       estimates are optimistic about allocator behaviour, so this pass
       just demotes oldest-first until the ledger says enough).
    """
    out: List[Demotion] = []
    tier = {s.name: s.tier for s in stats}
    # the demotion floor: a just-touched doc is never a victim (see
    # StoreBudgets.min_idle_s); every pass below works over this set
    by_age = sorted(
        (s for s in stats if s.idle_s(now) >= budgets.min_idle_s),
        key=lambda s: s.last_access,
    )

    if budgets.idle_cold_s > 0:
        for s in by_age:
            if tier[s.name] != TIER_COLD and s.idle_s(now) >= budgets.idle_cold_s:
                out.append(Demotion(s.name, TIER_COLD, "idle"))
                tier[s.name] = TIER_COLD

    if budgets.hot_docs > 0:
        hot = [s for s in by_age if tier[s.name] == TIER_HOT]
        for s in hot[: max(0, len(hot) - budgets.hot_docs)]:
            out.append(Demotion(s.name, TIER_WARM, "hot_budget"))
            tier[s.name] = TIER_WARM

    if budgets.warm_bytes > 0:
        live_bytes = sum(
            s.resident_bytes for s in stats if tier[s.name] != TIER_COLD
        )
        for s in by_age:
            if live_bytes <= budgets.warm_bytes:
                break
            if tier[s.name] == TIER_COLD:
                continue
            out.append(Demotion(s.name, TIER_COLD, "warm_budget"))
            tier[s.name] = TIER_COLD
            live_bytes -= s.resident_bytes

    if budgets.max_rss_bytes > 0 and rss_bytes is not None:
        excess = rss_bytes - budgets.max_rss_bytes
        for s in by_age:
            if excess <= 0:
                break
            if tier[s.name] == TIER_COLD:
                continue
            out.append(Demotion(s.name, TIER_COLD, "rss"))
            tier[s.name] = TIER_COLD
            # the estimate may undershoot what the allocator returns to
            # the OS; clamping at 1 byte guarantees forward progress so
            # sustained pressure eventually demotes everything demotable
            excess -= max(1, s.resident_bytes)

    # collapse duplicate names (a hot-budget victim may also be a
    # warm-bytes victim in the same sweep): the coldest decision wins,
    # keeping the first reason that named that tier
    best: dict = {}
    order: List[str] = []
    for d in out:
        prev = best.get(d.name)
        if prev is None:
            best[d.name] = d
            order.append(d.name)
        elif prev.to == TIER_WARM and d.to == TIER_COLD:
            best[d.name] = d
    return [best[n] for n in order]


def tier_counts(stats: List[DocStats]) -> Tuple[int, int, int]:
    hot = sum(1 for s in stats if s.tier == TIER_HOT)
    warm = sum(1 for s in stats if s.tier == TIER_WARM)
    cold = sum(1 for s in stats if s.tier == TIER_COLD)
    return hot, warm, cold
