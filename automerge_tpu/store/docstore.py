"""The tiered document store: bounded-memory residency for every
document a node serves.

One process used to hold every document it had ever opened fully
materialized — host op-store, optional device mirror, journal — so RSS
scaled linearly with the number of documents opened. ``DocStore``
replaces that assumption with an explicit residency state machine:

* **hot**  — device-resident ``DeviceDoc`` mirror + host op-store
  (write-hot docs; the device incremental-merge path stays warm).
* **warm** — host op-store only; the device mirror is dropped
  (read-mostly docs: every read serves, writes journal as always).
* **cold** — closed in memory entirely; on disk as the fsynced
  snapshot + journal tail the durability layer always maintains.
  The serving handle stays valid — the first access hydrates the
  document back to warm through the standard warm-recovery open
  (snapshot load in salvage mode + journal replay), under a per-doc
  single-flight lock so a stampede of requests for one cold document
  opens it exactly once.

Demotion is policy-driven (store/policy.py): LRU order under the
``AUTOMERGE_TPU_STORE_HOT_DOCS`` / ``_WARM_BYTES`` budgets, a hard
``_MAX_RSS`` process watermark, and an optional idle age-out — fed by
the same per-document accounting the obs layer already exports
(``doc.journal_bytes`` / ``doc.last_access_seconds`` /
``doc.resident_ops`` / ``doc.device_bytes``).

The store owns *bookkeeping and policy*; the *mechanics* of each
transition (reopening a journal, aliasing an RPC handle, dropping a
device mirror, detaching a replication stream) belong to the serving
layer, which supplies them as an ``ops`` object:

    ops.open_cold(name)         -> live document (hydration)
    ops.close_cold(name, compact) -> ColdDocRef (demotion to cold)
    ops.drop_device(name)       -> None (hot -> warm)
    ops.build_device(name)      -> bool (warm -> hot promotion)

Observability: ``store.tier{tier=...}`` gauges track the population,
``store.promotions`` / ``store.demotions`` counters carry
``{from,to,reason}`` labels, ``store.hydrate`` is the cold-open
latency histogram, and every transition lands a flight-recorder event
(``store.transition``).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from .. import obs
from ..degrade import brownout_active
from .policy import (
    TIER_COLD,
    TIER_HOT,
    TIER_WARM,
    DocStats,
    StoreBudgets,
    compact_on_demote,
    current_rss_bytes,
    device_resident_bytes,
    pick_demotions,
)


class StoreBackpressure(Exception):
    """Too many cold documents hydrating at once — retry. Carries the
    ``retriable`` flag the RPC error envelope and the reference client
    understand (the same contract as the shard pool's Backpressure)."""

    retriable = True


class ColdDocRef:
    """What a cold document leaves behind in the serving handle table:
    a few dozen bytes instead of a materialized document. Duck-types
    just enough of the durable wrapper for the handle-table code paths
    that may touch it without hydrating — ``journal.fsync_policy`` and
    ``doc.text_encoding`` for ``openDurable``'s mismatch checks,
    ``close()`` for ``free``/shutdown sweeps, and the frozen
    replication coordinates (nothing changes on disk while cold, so the
    values captured at demotion stay exact) for ``clusterStatus``."""

    _closed = True  # the residency check every access path keys on

    __slots__ = ("name", "fsync_policy", "text_encoding",
                 "_acked", "_appended", "replication_cursor")

    def __init__(self, name: str, *, fsync_policy: str,
                 text_encoding, acked: int, appended: int,
                 replication_cursor: Optional[bytes]):
        self.name = name
        self.fsync_policy = fsync_policy
        self.text_encoding = text_encoding
        self._acked = acked
        self._appended = appended
        self.replication_cursor = replication_cursor

    # openDurable reads live.journal.fsync_policy / live.doc.text_encoding
    @property
    def journal(self):
        return self

    @property
    def doc(self):
        return self

    def acked_prefix(self):
        return (self._acked, self._appended)

    def close(self) -> None:  # already closed; sweeps may call anyway
        return None


class _Entry:
    """Store-side bookkeeping for one named document."""

    __slots__ = ("name", "tier", "last_access", "want_device",
                 "resident_bytes", "lock", "doc")

    def __init__(self, name: str, tier: str, *, want_device: bool):
        self.name = name
        self.tier = tier
        self.last_access = obs.now()
        self.want_device = want_device
        self.resident_bytes = 0
        # single-flight guard for this document's tier transitions: a
        # stampede of readers for one cold doc serializes here and every
        # waiter past the first finds the document already live
        self.lock = threading.Lock()
        self.doc = None  # the live durable doc (None while cold)


class DocStore:
    """Tiered residency over every named durable document. See module
    docstring for the state machine; see ``StoreBudgets`` for knobs."""

    def __init__(self, ops, budgets: Optional[StoreBudgets] = None):
        self.ops = ops
        self.budgets = budgets or StoreBudgets.from_env()
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}
        # running tier populations (kept exact under _lock at every
        # transition): the gauges, the hot-budget check on the promote
        # path and status() must not scan 10^5 entries per access
        self._counts: Dict[str, int] = {
            TIER_HOT: 0, TIER_WARM: 0, TIER_COLD: 0}
        self._hydrations = threading.Semaphore(
            max(1, self.budgets.max_hydrations))
        self._evict_thread: Optional[threading.Thread] = None
        self._evict_wake = threading.Event()
        self._last_inline_sweep = 0.0
        self._closed = False
        self._export_tier_gauges()

    # -- admission / bookkeeping ---------------------------------------------

    def admit(self, name: str, dd, *, device: bool) -> None:
        """Register a freshly opened durable document (tier hot when it
        carries a device mirror, warm otherwise) and run the budgets."""
        tier = TIER_HOT if device else TIER_WARM
        with self._lock:
            e = self._entries.get(name)
            if e is None:
                e = self._entries[name] = _Entry(
                    name, tier, want_device=device)
                self._counts[tier] += 1
            else:
                self._counts[e.tier] -= 1
                self._counts[tier] += 1
                e.tier = tier
                e.want_device = e.want_device or device
            e.doc = dd
            e.last_access = obs.now()
            e.resident_bytes = _resident_bytes(dd)
        self._export_tier_gauges()
        self._maybe_start_evictor()
        self.request_evict()

    def forget(self, name: str) -> None:
        """Drop the entry entirely (the document was freed/closed by the
        serving layer; its on-disk state is not the store's concern)."""
        with self._lock:
            e = self._entries.pop(name, None)
            if e is not None:
                self._counts[e.tier] -= 1
        self._export_tier_gauges()

    def touch(self, name: str) -> None:
        """Per-request recency stamp. Deliberately lock-free on the
        common path: dict lookup and float store are GIL-atomic, the
        stamp is advisory (the policy also reads the live doc's own
        ``last_access``), and a store-wide lock here would serialize
        every shard worker on one mutex per request."""
        e = self._entries.get(name)
        if e is None:
            return
        e.last_access = obs.now()
        # a warm doc that wants its device mirror back promotes on
        # access (reads included — a read-hot doc earns residency too)
        if e.tier == TIER_WARM and e.want_device:
            self._maybe_promote(e)

    def tier(self, name: str) -> Optional[str]:
        with self._lock:
            e = self._entries.get(name)
            return e.tier if e is not None else None

    def names(self, tier: Optional[str] = None) -> List[str]:
        with self._lock:
            return sorted(
                n for n, e in self._entries.items()
                if tier is None or e.tier == tier
            )

    # -- access (the hydration path) -----------------------------------------

    def ensure_open(self, name: str):
        """Return the live document for ``name``, hydrating a cold one
        through the single-flight lock. Raises ``StoreBackpressure``
        (retriable) when more than ``max_hydrations`` *different* cold
        documents are mid-open — the stampede-on-one-doc case instead
        blocks on the entry lock and finds the document live."""
        with self._lock:
            e = self._entries.get(name)
        if e is None:
            raise KeyError(f"unknown stored document {name!r}")
        if (
            e.tier != TIER_COLD
            and e.doc is not None
            and not getattr(e.doc, "_closed", False)
        ):
            e.last_access = obs.now()
            self._maybe_promote(e)
            return e.doc
        with e.lock:
            if e.tier != TIER_COLD and e.doc is not None:
                if getattr(e.doc, "_closed", False):
                    # a reopen is mid-flight elsewhere (durableReopen's
                    # window): hydrating here would race it onto the
                    # journal flock — hand the client a retriable error
                    raise StoreBackpressure(
                        f"document {e.name!r} is reopening; retry"
                    )
                e.last_access = obs.now()
                return e.doc  # another thread hydrated while we waited
            if not self._hydrations.acquire(blocking=False):
                obs.count("store.hydrate_backpressure")
                raise StoreBackpressure(
                    f"too many cold documents hydrating; retry {name!r}"
                )
            try:
                with obs.span("store.hydrate", doc=name):
                    dd = self.ops.open_cold(name)
            finally:
                self._hydrations.release()
            with self._lock:
                e.doc = dd
                self._counts[e.tier] -= 1
                self._counts[TIER_WARM] += 1
                e.tier = TIER_WARM
                e.last_access = obs.now()
                e.resident_bytes = _resident_bytes(dd)
        self._transition(name, TIER_COLD, TIER_WARM, "access")
        obs.count("store.promotions", labels={
            "from": TIER_COLD, "to": TIER_WARM, "reason": "access"})
        self._export_tier_gauges()
        self.request_evict()
        return dd

    def _maybe_promote(self, e: _Entry) -> None:
        """Warm doc that wants a device mirror, with hot-budget room (or
        no hot budget at all): promote on access. Runs outside the store
        lock; the entry lock serializes against a racing demotion."""
        if not (e.want_device and e.tier == TIER_WARM):
            return
        if not e.lock.acquire(blocking=False):
            return  # a transition is in flight; this access keeps the doc
        try:
            if e.tier != TIER_WARM or e.doc is None:
                return
            if (
                self.budgets.hot_docs
                and self._counts[TIER_HOT] >= self.budgets.hot_docs
            ):
                return
            if self.ops.build_device(e.name):
                with self._lock:
                    self._counts[e.tier] -= 1
                    self._counts[TIER_HOT] += 1
                    e.tier = TIER_HOT
                    e.resident_bytes = _resident_bytes(e.doc)
                self._transition(e.name, TIER_WARM, TIER_HOT, "access")
                obs.count("store.promotions", labels={
                    "from": TIER_WARM, "to": TIER_HOT, "reason": "access"})
                self._export_tier_gauges()
        finally:
            e.lock.release()

    # -- demotion ------------------------------------------------------------

    def demote(self, name: str, to: str, reason: str = "manual") -> str:
        """Explicit demotion (the ``storeDemote`` RPC / CI drive). Also
        the single implementation the eviction sweep calls. Returns the
        resulting tier."""
        if to not in (TIER_WARM, TIER_COLD):
            raise ValueError(f"cannot demote to {to!r}")
        with self._lock:
            e = self._entries.get(name)
        if e is None:
            raise KeyError(f"unknown stored document {name!r}")
        with e.lock:
            frm = e.tier
            if frm == TIER_COLD or (frm == TIER_WARM and to == TIER_WARM):
                return e.tier
            if frm == TIER_HOT:
                self.ops.drop_device(name)
                with self._lock:
                    self._counts[TIER_HOT] -= 1
                    self._counts[TIER_WARM] += 1
                    e.tier = TIER_WARM
                    if e.doc is not None:
                        e.resident_bytes = _resident_bytes(e.doc)
                self._transition(name, TIER_HOT, TIER_WARM, reason)
                obs.count("store.demotions", labels={
                    "from": TIER_HOT, "to": TIER_WARM, "reason": reason})
            if to == TIER_COLD:
                compact = False
                if e.doc is not None:
                    compact = compact_on_demote(
                        e.doc.journal.size_bytes,
                        getattr(e.doc, "_run_image", None) is not None,
                        len(e.doc._core.history),
                        self.budgets,
                    )
                self.ops.close_cold(name, compact=compact)
                with self._lock:
                    self._counts[e.tier] -= 1
                    self._counts[TIER_COLD] += 1
                    e.doc = None
                    e.tier = TIER_COLD
                    e.resident_bytes = 0
                self._transition(name, TIER_WARM, TIER_COLD, reason)
                obs.count("store.demotions", labels={
                    "from": TIER_WARM, "to": TIER_COLD, "reason": reason})
        self._export_tier_gauges()
        return self.tier(name) or TIER_COLD

    def request_evict(self) -> None:
        """Admission-path eviction signal. A sweep is O(live entries),
        so the hot paths (admit, hydrate) must not each pay one — with
        a background sweeper running this just wakes it; without one it
        runs an inline sweep at most every 50ms. Budget overshoot is
        bounded by (admission rate x that latency), which the watermark
        headroom absorbs."""
        if self._closed or not self.budgets.active:
            return
        if self._evict_thread is not None:
            self._evict_wake.set()
            return
        now = obs.now()
        if now - self._last_inline_sweep >= 0.05:
            self._last_inline_sweep = now
            self.maybe_evict()

    def maybe_evict(self) -> int:
        """One policy sweep: snapshot accounting, ask the policy for
        victims, apply them. Returns the number of demotions applied.
        Cheap no-op when no budget is configured."""
        if self._closed or not self.budgets.active:
            return 0
        if brownout_active() and not (
            self.budgets.max_rss_bytes
            and current_rss_bytes() > self.budgets.max_rss_bytes
        ):
            # brownout: cold-demotion churn (close/compact/re-hydrate
            # cycles) defers — EXCEPT when RSS is actually over budget;
            # the memory watermark is a hard promise, degraded or not
            obs.count("store.evict_deferred_brownout")
            return 0
        now = obs.now()
        with self._lock:
            stats = []
            for e in self._entries.values():
                la = e.last_access
                if e.doc is not None:
                    # the durable layer stamps the doc on every ack and
                    # every read-path touch; take the freshest of the two
                    la = max(la, getattr(e.doc, "last_access", 0.0))
                    e.resident_bytes = _resident_bytes(e.doc)
                stats.append(DocStats(
                    e.name, e.tier, la, e.resident_bytes))
        rss = (
            current_rss_bytes() if self.budgets.max_rss_bytes else None
        )
        n = 0
        for d in pick_demotions(stats, self.budgets, now=now, rss_bytes=rss):
            try:
                self.demote(d.name, d.to, d.reason)
                n += 1
            except KeyError:
                continue  # freed while the sweep ran
            except Exception as e:  # noqa: BLE001 — one doc, not the sweep
                obs.count("store.demote_error", error=str(e)[:200])
        return n

    # -- the background sweeper ----------------------------------------------

    def _maybe_start_evictor(self) -> None:
        if (
            self._evict_thread is not None
            or not self.budgets.active
            or self.budgets.evict_interval_s <= 0
            or self._closed
        ):
            return
        with self._lock:
            if self._evict_thread is not None or self._closed:
                return
            self._evict_thread = threading.Thread(
                target=self._evict_loop, name="store-evict", daemon=True)
            self._evict_thread.start()

    def _evict_loop(self) -> None:
        while not self._closed:
            self._evict_wake.wait(self.budgets.evict_interval_s)
            self._evict_wake.clear()
            if self._closed:
                return
            try:
                self.maybe_evict()
            except Exception as e:  # noqa: BLE001 — sweeper must not die
                obs.count("store.evict_error", error=str(e)[:200])

    def close(self) -> None:
        """Stop the sweeper and drop bookkeeping. Does NOT close the
        documents — the serving layer's shutdown flush owns that."""
        self._closed = True
        self._evict_wake.set()
        t = self._evict_thread
        if t is not None:
            t.join(timeout=10)
            self._evict_thread = None
        with self._lock:
            self._entries.clear()

    # -- introspection -------------------------------------------------------

    def status(self, *, docs: bool = False) -> dict:
        with self._lock:
            counts = dict(self._counts)
            entries = list(self._entries.values()) if docs else []
        now = obs.now()
        per_doc = {}
        for e in entries:
            per_doc[e.name] = {
                "tier": e.tier,
                "idleSeconds": round(max(0.0, now - e.last_access), 3),
                "residentBytes": e.resident_bytes,
            }
        out = {
            "enabled": self.budgets.active,
            "tiers": counts,
            "budgets": {
                "hotDocs": self.budgets.hot_docs,
                "warmBytes": self.budgets.warm_bytes,
                "maxRssBytes": self.budgets.max_rss_bytes,
                "idleColdSeconds": self.budgets.idle_cold_s,
                "maxHydrations": self.budgets.max_hydrations,
            },
            "rssBytes": current_rss_bytes(),
        }
        if docs:
            out["docs"] = per_doc
        return out

    # -- internals -----------------------------------------------------------

    def _transition(self, name: str, frm: str, to: str, reason: str) -> None:
        # obs.event always lands in the flight recorder's bounded event
        # ring — every tier transition is reconstructable post-mortem
        obs.event("store.transition", doc=name, tier_from=frm, tier_to=to,
                  reason=reason)

    def _export_tier_gauges(self) -> None:
        with self._lock:
            counts = dict(self._counts)
        for t, n in counts.items():
            obs.gauge_set("store.tier", n, labels={"tier": t})


# measured floor for one live durable doc (AutoDoc + core document +
# op-store indexes + journal buffers) before any history payload: ~32KiB
# on CPython 3.10. The payload proxy below scales with history; without
# this floor a million EMPTY docs would look free to the warm-bytes
# budget while actually costing tens of GiB.
DOC_OVERHEAD_BYTES = 48 << 10


def _resident_bytes(dd) -> int:
    """Estimated host+device footprint of a live durable document. A
    proxy, not an accounting: a fixed per-doc overhead floor, plus
    snapshot-size + journal-size tracking the op-store's history
    payload, plus the device mirror's resolution arrays (exact). The
    policy only needs a consistent ordering and a roughly linear
    scale."""
    try:
        n = (DOC_OVERHEAD_BYTES + getattr(dd, "_last_snapshot_bytes", 0)
             + dd.journal.size_bytes)
        # the retained run-coded image (storage/runsnap.py) is real host
        # memory a warm doc holds to make promotion/compaction decode-only
        img = getattr(dd, "_run_image", None)
        if img is not None:
            n += img.nbytes
    except Exception:  # closed mid-estimate
        return 0
    dev = getattr(dd, "device_doc", None)
    if dev is not None:
        # TRUE device-path bytes (compressed resident columns +
        # readbacks), so a hot doc whose history compresses 10x is 10x
        # cheaper to the hot budget than one that doesn't
        n += device_resident_bytes(dev)
    return n
