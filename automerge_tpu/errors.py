"""Typed error hierarchy (the analogue of reference error.rs:1-134).

Every framework error derives from ``AutomergeError`` (itself a
``ValueError`` so existing broad handlers keep working). The typed
subclasses mirror the reference's ``AutomergeError`` enum variants that
carry semantic meaning callers dispatch on; parse-layer errors
(ChunkParseError, LEBDecodeError, ...) live with their codecs and are
re-exported here.
"""

from __future__ import annotations


class AutomergeError(ValueError):
    """Base class for all framework errors (reference: error.rs)."""


class MissingCounter(AutomergeError):
    """Increment of a property that holds no counter
    (reference: error.rs AutomergeError::MissingCounter)."""

    def __init__(self, msg="increment of a non-counter value"):
        super().__init__(msg)


class InvalidOp(AutomergeError):
    """Operation not valid for the target object's type
    (reference: error.rs AutomergeError::InvalidOp(ObjType))."""

    def __init__(self, obj_type=None, msg=None):
        self.obj_type = obj_type
        super().__init__(msg or f"invalid op for object type {obj_type}")


class DuplicateSeqNumber(AutomergeError):
    """A change re-used a (actor, seq) slot
    (reference: error.rs DuplicateSeqNumber)."""

    def __init__(self, seq=None, actor=None):
        self.seq = seq
        self.actor = actor
        super().__init__(f"duplicate seq {seq} for actor {actor}")


class MissingDeps(AutomergeError):
    """Changes could not be applied for want of their dependencies
    (reference: error.rs MissingDeps)."""


class InvalidHash(AutomergeError):
    """A change hash failed verification or was malformed
    (reference: error.rs InvalidHash)."""


class MissingHash(AutomergeError):
    """A requested change hash is not in this document's history
    (reference: error.rs MissingHash)."""


class InvalidObjId(AutomergeError):
    """An object/op id string failed to resolve
    (reference: error.rs InvalidObjId / InvalidObjIdFormat)."""


class InvalidActorId(AutomergeError):
    """An actor id string failed to parse
    (reference: error.rs InvalidActorId)."""


class InvalidIndex(AutomergeError):
    """A sequence index is out of bounds
    (reference: error.rs InvalidIndex)."""


class IntegrityError(AutomergeError):
    """Stored or replicated state failed integrity verification — a
    digest mismatch, a corrupt snapshot chunk, or a journal record whose
    checksum no longer matches its bytes. Never retriable: retrying the
    same read returns the same corrupt bytes; repair (scrub self-heal,
    peer re-fetch, or salvage) has to happen first."""

    retriable = False


# parse-layer errors are defined with their codecs and resolved lazily so
# importing this module never pulls the whole package; the static name map
# keeps __getattr__ inert for every other lookup (dunder probes during
# import would otherwise recurse into half-initialized modules)
_LAZY = {
    "ChangeGraphError": ".core.change_graph",
    "ChunkParseError": ".storage.chunk",
    "ColumnLayoutError": ".storage.columns",
    "ExtractError": ".ops.extract",
    "LEBDecodeError": ".utils.leb128",
    "OpStoreError": ".core.op_store",
    "SyncError": ".sync.protocol",
}


def __getattr__(name):
    mod_name = _LAZY.get(name)
    if mod_name is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    mod = importlib.import_module(mod_name, __package__)
    return getattr(mod, name)


__all__ = [
    "AutomergeError",
    "ChangeGraphError",
    "ChunkParseError",
    "ColumnLayoutError",
    "DuplicateSeqNumber",
    "ExtractError",
    "IntegrityError",
    "InvalidActorId",
    "InvalidHash",
    "InvalidIndex",
    "InvalidObjId",
    "InvalidOp",
    "LEBDecodeError",
    "MissingCounter",
    "MissingDeps",
    "MissingHash",
    "OpStoreError",
    "SyncError",
]
