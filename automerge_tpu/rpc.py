"""Line-delimited JSON-RPC frontend over stdio: the second embedding
boundary.

The reference ships two FFI frontends: a C API and a wasm-bindgen module
whose role is to let ANOTHER language runtime (JS) drive documents through
a narrow marshalled surface (reference: rust/automerge-wasm/src/lib.rs:102-
1083 — the ~80-method Automerge class). This frontend plays that role for
any language with a subprocess + JSON: one request per line on stdin, one
response per line on stdout.

Protocol:
    -> {"id": 1, "method": "create", "params": {"actor": "<hex>"}}
    <- {"id": 1, "result": {"doc": 1}}
    -> {"id": 2, "method": "spliceText",
        "params": {"doc": 1, "obj": "1@..", "pos": 0, "del": 0, "text": "hi"}}
    <- {"id": 2, "result": null}
Errors come back as {"id": n, "error": {"type": "...", "message": "..."}}
and never kill the server. Bytes (saves, changes, sync messages, hashes)
travel base64. Values are JSON-native with two wrappers for types JSON
cannot express: {"$counter": n}, {"$timestamp": ms}, {"$bytes": "<b64>"};
object creation returns {"$obj": "<exid>", "type": "map|list|text"}.

Run: ``python -m automerge_tpu.rpc`` (see tests/test_rpc.py for a full
two-peer session driven from a separate process).

Robustness: every malformed frame (bad JSON, unknown method, oversized
request, undecodable base64) answers with an ``error`` response; EOF —
even mid-request — is a clean shutdown. ``configure`` sets
``maxRequestBytes`` and ``syncTimeoutMs``; the ``syncSession*`` methods
expose the resilient retry/backoff/reset sync sessions (sync/session.py)
for lossy client links, and ``load`` accepts ``onError: "salvage"`` to
recover damaged saves (the response then carries a ``salvage`` report).

Durability: ``python -m automerge_tpu.rpc --durable DIR`` enables
``openDurable {"name": ...}`` — each named document persists under
``DIR/<name>`` through the crash-safe journal + snapshot layer
(storage/durable.py), so every committed or sync-absorbed change is on
disk before the response goes out; ``durableInfo`` / ``durableCompact``
expose the journal state.

Concurrency: ``--socket HOST:PORT`` / ``--unix PATH`` serve the same
protocol concurrently (serve/server.py) — per-document single-writer
shards, bounded queues with a ``Backpressure`` error, group-commit
durable acks, coalesced sync receives. The stdio mode here stays a
strictly serial single-client loop.

Observability: every request is counted and timed into the labeled
metrics registry (``rpc.request{method=...}`` latency histograms,
``rpc.bytes_in``/``rpc.bytes_out``, ``rpc.errors{method=,type=}``,
``rpc.request_bytes``), and the ``metrics`` method returns the whole
registry — Prometheus text by default, ``{"format": "json"}`` for the
structured snapshot — so an operator can scrape a running server over
the same stdio channel.
"""

from __future__ import annotations

import base64
import json
import os
import sys
import threading
import time
from typing import Dict, Optional

from . import obs
from .api import AutoDoc
from .degrade import brownout_active
from .obs import heat as _heat
from .sync import SessionConfig, SyncSession, SyncState
from .types import ActorId, ObjType, ScalarValue

# default per-request line limit: large enough for multi-megabyte base64
# saves, small enough that a hostile or broken client cannot buffer-bomb
# the process — serve() reads each line with a bounded readline(limit), so
# an endless newline-free stream is discarded in bounded chunks instead of
# being buffered whole (configurable via the ``configure`` method)
DEFAULT_MAX_REQUEST_BYTES = 32 << 20
DEFAULT_SYNC_TIMEOUT_MS = 5000

# durable doc names become directory names under --durable DIR: one safe
# path component, no leading dot
import re as _re

_DURABLE_NAME_RE = _re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")

_OBJTYPES = {"map": ObjType.MAP, "list": ObjType.LIST, "text": ObjType.TEXT,
             "table": ObjType.TABLE}


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode("ascii")


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


def _to_scalar(v) -> ScalarValue:
    """JSON value -> ScalarValue (wrappers for counter/timestamp/bytes)."""
    if isinstance(v, dict):
        if "$counter" in v:
            return ScalarValue("counter", int(v["$counter"]))
        if "$timestamp" in v:
            return ScalarValue("timestamp", int(v["$timestamp"]))
        if "$bytes" in v:
            return ScalarValue("bytes", _unb64(v["$bytes"]))
        raise ValueError(f"unsupported value wrapper {sorted(v)}")
    if v is None:
        return ScalarValue("null")
    if isinstance(v, bool):
        return ScalarValue("bool", v)
    if isinstance(v, int):
        return ScalarValue("int", v)
    if isinstance(v, float):
        return ScalarValue("f64", v)
    if isinstance(v, str):
        return ScalarValue("str", v)
    raise ValueError(f"unsupported value type {type(v).__name__}")


def _from_rendered(rendered, exid, doc) -> object:
    """(kind, payload) from doc.get/get_all -> JSON value."""
    kind = rendered[0]
    if kind == "obj":
        t = doc.object_type(exid)
        return {"$obj": exid, "type": t.name.lower()}
    if kind == "counter":
        return {"$counter": int(rendered[1])}
    sv = rendered[1]
    if sv.tag == "bytes":
        return {"$bytes": _b64(sv.value)}
    if sv.tag == "timestamp":
        return {"$timestamp": int(sv.value)}
    if sv.tag == "counter":
        return {"$counter": int(sv.value)}
    if sv.tag == "null":
        return None
    if sv.tag == "unknown":
        return {"$bytes": _b64(bytes(sv.value[1]))}
    return sv.value


class _StoreOps:
    """The tier-transition mechanics the DocStore delegates back to the
    serving layer (store/docstore.py owns policy + bookkeeping only)."""

    __slots__ = ("_rpc",)

    def __init__(self, rpc: "RpcServer"):
        self._rpc = rpc

    def open_cold(self, name):
        return self._rpc._store_open_cold(name)

    def close_cold(self, name, compact):
        return self._rpc._store_close_cold(name, compact=compact)

    def drop_device(self, name):
        return self._rpc._store_drop_device(name)

    def build_device(self, name):
        return self._rpc._store_build_device(name)


class DeadlineExceeded(Exception):
    """The client's ``deadlineMs`` budget expired before the server
    reached this stage — the request was answered WITHOUT executing the
    mutation (the client already gave up; doing the work anyway only
    deepens the overload). Always retriable: the client may still want
    the operation under a fresh budget."""

    retriable = True


def request_expired(req: dict) -> bool:
    """True when the request carried ``deadlineMs`` and its stamped
    local expiry (see ``_parse_line``) has passed."""
    dl = req.get("_deadline_ts")
    return dl is not None and obs.now() >= dl


def deadline_response(rid, method: str, stage: str) -> dict:
    """The ``DeadlineExceeded`` answer for one expired request, counted
    per enforcement stage (``serve.deadline_expired{stage}``)."""
    obs.count("serve.deadline_expired", labels={"stage": stage})
    obs.count("rpc.errors", labels={"method": method or "unknown",
                                    "type": "DeadlineExceeded"})
    return {"id": rid, "error": {
        "type": "DeadlineExceeded",
        "message": f"client deadline expired before {stage}",
        "retriable": True,
    }}


class RpcServer:
    """One frontend session: documents + sync states by integer handle."""

    def __init__(
        self,
        max_request_bytes: int = DEFAULT_MAX_REQUEST_BYTES,
        sync_timeout_ms: int = DEFAULT_SYNC_TIMEOUT_MS,
        durable_dir: Optional[str] = None,
    ):
        self._docs: Dict[int, AutoDoc] = {}
        self._syncs: Dict[int, SyncState] = {}
        self._sessions: Dict[int, SyncSession] = {}
        self._patched = set()  # docs with an activated patch cursor
        self._next = 1
        self.max_request_bytes = max_request_bytes
        self.sync_timeout_ms = sync_timeout_ms
        # --durable DIR mode: named documents persist under DIR/<name> via
        # the crash-safe journal + snapshot layer (storage/durable.py)
        self.durable_dir = durable_dir
        self._durable_names: Dict[str, int] = {}  # name -> open handle
        # handle-table guard: the socket serving layer (serve/) registers
        # and frees handles from many threads; stdio mode pays one
        # uncontended RLock acquisition per registration
        self._lock = threading.RLock()
        # session handle -> doc handle, so the serving layer can route
        # session-only requests (poll/receive/stats) to the doc's shard
        self._session_docs: Dict[int, int] = {}
        # (doc handle, peer) -> session handle for syncSessionAttach
        # idempotency within one server incarnation
        self._attached_sessions: Dict = {}
        # set by SocketRpcServer: durable docs opened through a concurrent
        # server compact on a background thread instead of the ack path
        self.serve_background_compact = False
        # cluster hook (cluster/node.py): called with (name, durable_doc)
        # after every FRESH openDurable, so a leader's replication hub
        # starts shipping the document's journal the moment it exists
        self.on_durable_open = None
        # serializes the name-cache check against the filesystem open,
        # PER NAME: a cluster node's replication path opens docs OUTSIDE
        # the serving layer's openDurable queue, and two concurrent
        # opens of one name would race each other onto the same journal
        # flock — but a slow open (multi-second journal replay) of one
        # document must not head-of-line-block opens of every other
        self._open_locks: Dict[str, threading.Lock] = {}
        # chaos mode (AUTOMERGE_TPU_CHAOS=1): durable docs open through a
        # per-doc FaultyFS so the chaosDisk method can deal a RUNNING
        # journal ENOSPC on append / EIO on fsync. Off (the default) the
        # injection surface does not exist at all.
        self.chaos_enabled = os.environ.get("AUTOMERGE_TPU_CHAOS") == "1"
        self._chaos_fs: Dict[str, object] = {}  # doc name -> FaultyFS
        # tiered residency (store/): every named durable document this
        # server serves is tracked in the DocStore, which demotes idle
        # documents hot -> warm -> cold under the configured budgets and
        # hydrates cold ones lazily on access. Unconfigured budgets (the
        # default) make it pure bookkeeping — nothing is ever demoted.
        self.store = None
        self._handle_names: Dict[int, str] = {}  # doc handle -> durable name
        # overload resilience: deadline enforcement shares the admission
        # master switch (AUTOMERGE_TPU_ADMISSION=0 is the uncontrolled
        # baseline the overload bench compares against). The serving
        # layer installs its AdmissionController here so cluster status
        # can advertise shed-mode.
        self.deadlines_enabled = (
            os.environ.get("AUTOMERGE_TPU_ADMISSION", "1") != "0")
        self.admission = None
        # integrity scrubber (integrity.py): the serving layer installs
        # and starts one per server; scrubNow lazily builds it so tests
        # and CI can force a round on a bare RpcServer too
        self.scrubber = None
        if durable_dir is not None:
            from .store import DocStore

            self.store = DocStore(_StoreOps(self))

    # -- handle plumbing ----------------------------------------------------

    def _reg(self, table, value) -> int:
        with self._lock:
            h = self._next
            self._next += 1
            table[h] = value
        return h

    def _doc(self, p) -> AutoDoc:
        doc = self._docs.get(p["doc"])
        if doc is None:
            raise ValueError(f"invalid doc handle {p.get('doc')}")
        if getattr(doc, "_closed", False) and self.store is not None:
            # a cold-demoted document: hydrate it (single-flight, inside
            # this doc's ordered queue) before serving the request
            doc = self._ensure_resident(p["doc"])
        touch = getattr(doc, "touch", None)
        if touch is not None and not brownout_active():
            # read-path recency: without this a read-hot document looks
            # idle to the store's LRU policy (writes refresh at ack exit,
            # reads previously refreshed nothing). In brownout the skip
            # is deliberate: reads and generateSyncMessage serve from
            # the resident image without recency churn — LRU precision
            # is what the degraded mode trades for capacity.
            touch()
            if self.store is not None:
                self.store.touch(self._handle_names.get(p["doc"], ""))
        return doc

    def _ensure_resident(self, h):
        """The document behind handle ``h``, hydrated if it was demoted
        to cold (may raise the retriable ``StoreBackpressure`` past the
        store's concurrent-hydration bound). None for unknown handles."""
        doc = self._docs.get(h)
        if (
            doc is not None
            and getattr(doc, "_closed", False)
            and self.store is not None
        ):
            name = self._handle_names.get(h)
            if name is not None:
                doc = self.store.ensure_open(name)
        return doc

    def _heads(self, p, key="heads"):
        hs = p.get(key)
        return None if hs is None else [_unb64(h) for h in hs]

    # -- methods (wasm lib.rs surface, JSON-shaped) -------------------------

    def create(self, p):
        actor = bytes.fromhex(p["actor"]) if p.get("actor") else None
        doc = AutoDoc(
            actor=ActorId(actor) if actor else None,
            text_encoding=p.get("textEncoding"),
        )
        return {"doc": self._reg(self._docs, doc)}

    def load(self, p):
        doc = AutoDoc.load(
            _unb64(p["data"]),
            text_encoding=p.get("textEncoding"),
            on_error=p.get("onError"),
        )
        out = {"doc": self._reg(self._docs, doc)}
        rep = doc.salvage_report
        if rep is not None:
            out["salvage"] = {
                "appliedChunks": rep.applied_chunks,
                "dropped": [
                    {"offset": d.offset, "reason": d.reason,
                     "checksum": _b64(d.checksum)}
                    for d in rep.dropped
                ],
            }
        return out

    def configure(self, p):
        """Runtime knobs: syncTimeoutMs (resilient sync sessions' base
        retransmit timeout), maxRequestBytes (per-line request limit)."""
        if "syncTimeoutMs" in p:
            v = int(p["syncTimeoutMs"])
            if v <= 0:
                raise ValueError("syncTimeoutMs must be positive")
            self.sync_timeout_ms = v
        if "maxRequestBytes" in p:
            v = int(p["maxRequestBytes"])
            if v <= 0:
                raise ValueError("maxRequestBytes must be positive")
            self.max_request_bytes = v
        return {"syncTimeoutMs": self.sync_timeout_ms,
                "maxRequestBytes": self.max_request_bytes}

    def free(self, p):
        with self._lock:
            doc = self._docs.pop(p["doc"], None)
            self._patched.discard(p["doc"])
            # sessions attached to this doc die with it: they hold the
            # (soon-closed) durable wrapper, and a long-lived server that
            # re-attaches per restart/failover must not leak them
            stale = [h for (d, _peer), h in self._attached_sessions.items()
                     if d == p["doc"]]
            for h in stale:
                self._sessions.pop(h, None)
                self._session_docs.pop(h, None)
            self._attached_sessions = {
                k: h for k, h in self._attached_sessions.items()
                if k[0] != p["doc"]
            }
            name = None
            if doc is not None and hasattr(doc, "journal"):  # durable wrapper
                # drop the name mapping BEFORE closing: if close raises,
                # the name must not stay pointed at a dead handle
                self._durable_names = {
                    n: h for n, h in self._durable_names.items()
                    if h != p["doc"]
                }
                name = self._handle_names.pop(p["doc"], None)
        if doc is not None and hasattr(doc, "journal"):
            if self.store is not None and name is not None:
                self.store.forget(name)
            doc.close()
        # cardinality hygiene: the shard pool keys this doc's queue by
        # its integer handle — drop the rpc.queue_depth{doc=<handle>}
        # series along with the per-doc gauges (handles are unbounded
        # over a server's life; the gauge table must not be)
        obs.remove_doc_gauges(name, queue_key=p.get("doc"))
        return None

    # -- durable documents (--durable DIR mode) -----------------------------

    def _durable_path(self, name: str) -> str:
        import os

        if self.durable_dir is None:
            raise ValueError("server is not running in --durable mode")
        if not isinstance(name, str) or not _DURABLE_NAME_RE.match(name):
            raise ValueError(f"invalid durable doc name {name!r}")
        return os.path.join(self.durable_dir, name)

    def openDurable(self, p):
        """Open (or create) the named durable document under the server's
        --durable directory; reopening an already-open name returns the
        same handle (two live journals on one file would corrupt it).
        ``device: true`` additionally recovers a resident DeviceDoc whose
        incremental path absorbs sync-received changes."""
        name = p.get("name")
        path = self._durable_path(name)
        with self._lock:
            lk = self._open_locks.setdefault(name, threading.Lock())
        with lk:
            return self._open_durable_locked(name, path, p)

    def _open_durable_locked(self, name, path, p):
        # the name-cache read and the live-handle check must be one
        # atomic snapshot: a concurrent free() pops both under this lock,
        # so we either see the live doc or neither — never a handle whose
        # journal a racing free is mid-close on
        with self._lock:
            h = self._durable_names.get(name)
            live = self._docs.get(h) if h is not None else None
        if live is not None:
            # a cached handle must not silently override the caller's
            # requested durability: error on a policy mismatch
            want = p.get("fsync")  # omitted = don't-care, like textEncoding
            if want is not None and want != live.journal.fsync_policy:
                raise ValueError(
                    f"durable doc {name!r} is already open with "
                    f"fsync={live.journal.fsync_policy!r}, not {want!r}"
                )
            want_enc = p.get("textEncoding")
            # normalize: a doc opened without an explicit encoding stores
            # None, which MEANS the process default — not a conflict with
            # a client naming that same default explicitly
            from .types import get_text_encoding

            have_enc = live.doc.text_encoding or get_text_encoding()
            if want_enc is not None and want_enc != have_enc:
                raise ValueError(
                    f"durable doc {name!r} is already open with "
                    f"textEncoding={have_enc!r}, not {want_enc!r}"
                )
            # a cold doc's handle answers without hydrating — residency
            # is paid on first real access, not on re-open
            if self.store is not None:
                self.store.touch(name)
            return {"doc": h}
        open_kw = {}
        if self.chaos_enabled:
            from .storage.crashsim import FaultyFS

            fs = self._chaos_fs.get(name)
            if fs is None:
                fs = self._chaos_fs[name] = FaultyFS()
            open_kw["fs"] = fs
        dd = AutoDoc.open(
            path,
            fsync=p.get("fsync", "always"),
            text_encoding=p.get("textEncoding"),
            device=bool(p.get("device", False)),
            background_compact=self.serve_background_compact,
            compact_cost_ratio=float(
                os.environ.get("AUTOMERGE_TPU_COMPACT_COST_RATIO", "0") or 0
            ),
            **open_kw,
        )
        h = self._reg(self._docs, dd)
        with self._lock:
            self._durable_names[name] = h
            self._handle_names[h] = name
        if self.on_durable_open is not None:
            self.on_durable_open(name, dd)
        if self.store is not None:
            self.store.admit(name, dd, device=bool(p.get("device", False)))
        return {"doc": h}

    def _durable_doc(self, p):
        doc = self._doc(p)
        if not hasattr(doc, "journal"):
            raise ValueError(f"doc handle {p.get('doc')} is not durable")
        return doc

    def durableCompact(self, p):
        doc = self._durable_doc(p)
        compacted = doc.compact()
        return {"compacted": compacted,
                "journalRecords": doc.journal.record_count}

    def durableInfo(self, p):
        doc = self._durable_doc(p)
        img = getattr(doc, "_run_image", None)
        return {
            "path": doc.path,
            "journalRecords": doc.journal.record_count,
            "journalBytes": doc.journal.size_bytes,
            "fsync": doc.journal.fsync_policy,
            "degraded": doc.degraded,
            "poisoned": doc.journal.poisoned_reason,
            # run-coded persistence surface: which codec the doc's
            # snapshot/image currently speaks, and the retained image's
            # host footprint (0 = legacy/chunk, no image retained)
            "snapshotCodec": "runsnap" if img is not None else "chunk",
            "runImageBytes": 0 if img is None else img.nbytes,
        }

    def durableReopen(self, p):
        """Close and re-open a named durable document in place — the
        operator recovery path for a doc degraded by a live disk fault
        (a poisoned journal re-acquires its file and flock; recovery
        replays snapshot + intact journal prefix). The handle is
        preserved, so clients holding it keep working; sessions attached
        to the old incarnation are dropped exactly as ``free`` drops
        them (re-attach resumes via the epoch handshake)."""
        name = p.get("name")
        path = self._durable_path(name)
        with self._lock:
            lk = self._open_locks.setdefault(name, threading.Lock())
        with lk:
            with self._lock:
                h = self._durable_names.get(name)
                old = self._docs.get(h) if h is not None else None
                # unmap the NAME (so the open below builds a fresh doc)
                # but keep the handle pointing at the old instance for
                # the whole reopen window: a concurrent request on it
                # answers with the doc's own (retriable) degraded error
                # rather than a bogus invalid-handle
                self._durable_names.pop(name, None)
            if old is not None:
                try:
                    old.close()
                except Exception as e:  # noqa: BLE001 — a degraded doc's
                    # close may trip on its own poisoned journal; the
                    # reopen below re-establishes a clean state anyway
                    obs.count("rpc.reopen_close_error", error=str(e)[:200])
            if p.get("wipe"):
                # the replica-reset path (anti-entropy repair of a
                # diverged copy): the fresh open must rebuild from
                # nothing — salvaging the old bytes would keep the very
                # corruption the reset is meant to remove
                from .storage.durable import JOURNAL_NAME, SNAPSHOT_NAME

                for fname in (SNAPSHOT_NAME, JOURNAL_NAME):
                    try:
                        os.remove(os.path.join(path, fname))
                    except OSError:
                        pass
            try:
                res = self._open_durable_locked(name, path, p)
            except Exception:
                # reopen failed (e.g. the disk fault is still live):
                # restore the name mapping so the doc stays addressable
                # (still degraded) and a later reopen can retry
                if h is not None:
                    with self._lock:
                        self._durable_names[name] = h
                raise
            new_h = res["doc"]
            with self._lock:
                if h is not None and new_h != h:
                    # preserve the caller's existing handle: alias it to
                    # the fresh doc and retire the transient handle the
                    # open minted (nobody ever saw it)
                    self._docs[h] = self._docs.pop(new_h)
                    self._durable_names[name] = h
                    self._handle_names.pop(new_h, None)
                    self._handle_names[h] = name
                    new_h = h
                # sessions attached to the old incarnation die with it
                # (re-attach resumes via the epoch handshake)
                if h is not None:
                    stale = [
                        sh for (d, _peer), sh in self._attached_sessions.items()
                        if d == h
                    ]
                    for sh in stale:
                        self._sessions.pop(sh, None)
                        self._session_docs.pop(sh, None)
                    self._attached_sessions = {
                        k: v for k, v in self._attached_sessions.items()
                        if k[0] != h
                    }
            obs.count("rpc.durable_reopens")
            return {"doc": new_h, "reopened": True}

    def chaosDisk(self, p):
        """Chaos-only fault injection (requires AUTOMERGE_TPU_CHAOS=1 in
        the server's environment): arm or clear a live disk fault on the
        named durable document's filesystem. ``op`` is one of write /
        truncate / fsync / replace / sync_dir / read; ``err`` an errno
        name (EIO, ENOSPC) or — for ``read`` only — ``BITFLIP``, which
        silently corrupts one bit of the bytes read instead of raising
        (the bit-rot model the integrity scrub exists to catch);
        ``count`` how many calls fail (-1 = until cleared);
        ``clear: true`` disarms (``op`` optional)."""
        if not self.chaos_enabled:
            raise ValueError(
                "chaosDisk requires AUTOMERGE_TPU_CHAOS=1 in the server "
                "environment"
            )
        name = p.get("name")
        fs = self._chaos_fs.get(name)
        if fs is None:
            raise ValueError(f"no chaos-wrapped durable doc {name!r} open")
        if p.get("clear"):
            fs.clear(p.get("op"))
        else:
            fs.arm(p["op"], p.get("err", "EIO"), int(p.get("count", -1)))
        return {"armed": {op: list(v) for op, v in fs.armed().items()}}

    def docDigest(self, p):
        """The verifiable state digest of one document: SHA-256 over
        (change-hash XOR accumulator, change count, sorted heads) —
        identical across residency modes and merge orders, so two nodes
        agree iff they hold the same state (integrity.py). Address by
        durable ``name`` (hydrates a cold doc; errors on names with no
        on-disk directory) or by ``doc`` handle."""
        name = p.get("name")
        if name is not None:
            path = self._durable_path(name)
            with self._lock:
                known = self._durable_names.get(name) is not None
            if not known and not os.path.isdir(path):
                raise ValueError(f"unknown durable doc {name!r}")
            h = self.openDurable({"name": name})["doc"]
            doc = self._ensure_resident(h)
            if doc is None:
                doc = self._docs[h]
        else:
            doc = self._doc(p)
        if hasattr(doc, "doc_digest"):
            return dict(doc.doc_digest())
        from . import integrity

        core = doc.doc if hasattr(doc, "doc") else doc
        return dict(integrity.doc_digest(core))

    def scrubNow(self, p):
        """Force one synchronous scrub round (integrity.Scrubber) and
        return its summary — the deterministic hook CI smokes use
        instead of sleeping out the background cadence."""
        s = self.scrubber
        if s is None:
            from .integrity import Scrubber

            s = self.scrubber = Scrubber(self)
        return s.run_round()

    # -- tiered residency mechanics (store/docstore.py drives these) ---------

    def _store_doc(self, name: str):
        """(handle, live durable doc) for a store transition; raises for
        unknown or already-cold names."""
        with self._lock:
            h = self._durable_names.get(name)
            dd = self._docs.get(h) if h is not None else None
        if h is None or dd is None or not hasattr(dd, "journal"):
            raise ValueError(f"durable doc {name!r} is not open")
        return h, dd

    def _store_open_cold(self, name: str):
        """Hydrate a cold document: reopen its directory through the
        standard warm-recovery path (salvage snapshot load + journal
        replay) and alias the existing client handle to the fresh
        instance. Runs under the store's per-doc single-flight lock."""
        h, ref = self._store_doc(name)
        path = self._durable_path(name)
        open_kw = {}
        if self.chaos_enabled:
            from .storage.crashsim import FaultyFS

            fs = self._chaos_fs.get(name)
            if fs is None:
                fs = self._chaos_fs[name] = FaultyFS()
            open_kw["fs"] = fs
        dd = AutoDoc.open(
            path,
            fsync=getattr(ref, "fsync_policy", "always"),
            text_encoding=getattr(ref, "text_encoding", None),
            device=False,  # cold hydrates to WARM; hot is a promotion
            background_compact=self.serve_background_compact,
            compact_cost_ratio=float(
                os.environ.get("AUTOMERGE_TPU_COMPACT_COST_RATIO", "0") or 0
            ),
            **open_kw,
        )
        with self._lock:
            self._docs[h] = dd
        if self.on_durable_open is not None:
            # replication: the hub reattaches the fresh journal in place
            # (followers whose cursors name the old stream resync via the
            # cursor-mismatch snapshot path)
            self.on_durable_open(name, dd)
        return dd

    def _store_close_cold(self, name: str, *, compact: bool = True):
        """Demote to cold: optionally compact (bounding the hydration
        replay), close the journal (flock released), drop the sessions
        attached to the document (clients re-attach; the epoch handshake
        resumes them, exactly as after ``durableReopen``), and leave a
        ``ColdDocRef`` placeholder on the handle so the materialized
        document — host op-store, device mirror, journal buffers — is
        garbage the moment the last request drains."""
        from .store import ColdDocRef

        h, dd = self._store_doc(name)
        if getattr(dd, "_closed", False):
            return dd  # already cold
        hub = getattr(self, "hub", None)
        if hub is not None:
            # a live stream must not keep shipping (or referencing) a
            # journal that is about to close; hydration re-attaches
            try:
                hub.detach(name)
            except Exception as e:  # noqa: BLE001 — demotion must win
                obs.count("store.demote_error", error=str(e)[:200])
        with dd.lock:
            if compact and not dd.degraded:
                dd.compact()
            dd.close()
            acked, appended = dd.acked_prefix()
            ref = ColdDocRef(
                name,
                fsync_policy=dd.journal.fsync_policy,
                text_encoding=dd._core.text_encoding,
                acked=acked,
                appended=appended,
                replication_cursor=dd.replication_cursor,
            )
        with self._lock:
            # every session holding the closed instance dies with it —
            # feeding a closed journal would poison-error the client
            stale = [sh for sh, d in self._session_docs.items() if d == h]
            for sh in stale:
                self._sessions.pop(sh, None)
                self._session_docs.pop(sh, None)
            self._attached_sessions = {
                k: v for k, v in self._attached_sessions.items()
                if k[0] != h
            }
            self._docs[h] = ref
        # dd.close() above already removed the per-doc gauges; the shard
        # queue's depth series is keyed by handle and needs its own drop
        obs.remove_doc_gauges(None, queue_key=h)
        return ref

    def _store_drop_device(self, name: str) -> None:
        """Demote hot -> warm: release the device mirror and detach it
        from live sessions (which would otherwise keep feeding — and
        keeping alive — the dropped arrays)."""
        h, dd = self._store_doc(name)
        with dd.lock:
            dev = dd.drop_device_mirror()
        if dev is not None:
            with self._lock:
                for sh, d in self._session_docs.items():
                    if d == h:
                        sess = self._sessions.get(sh)
                        if sess is not None:
                            sess.device_doc = None

    def _store_build_device(self, name: str) -> bool:
        """Promote warm -> hot: rebuild the device mirror and hand it to
        the document's live sessions."""
        h, dd = self._store_doc(name)
        try:
            dev = dd.build_device_mirror()
        except Exception as e:  # noqa: BLE001 — promotion is best-effort
            obs.count("store.promote_error", error=str(e)[:200])
            return False
        with self._lock:
            for sh, d in self._session_docs.items():
                if d == h:
                    sess = self._sessions.get(sh)
                    if sess is not None:
                        sess.device_doc = dev
        return True

    def storeStatus(self, p):
        """Tier population, budgets and process RSS; ``{"docs": true}``
        adds per-document tier/idle/footprint detail."""
        if self.store is None:
            raise ValueError("server is not running in --durable mode")
        return self.store.status(docs=bool(p.get("docs")))

    def storeDemote(self, p):
        """Explicitly demote a named document (``to``: "warm" or
        "cold") — the operator/CI surface over the same transition the
        LRU policy drives."""
        if self.store is None:
            raise ValueError("server is not running in --durable mode")
        name = p.get("name")
        if not isinstance(name, str):
            raise ValueError("storeDemote requires a doc name")
        tier = self.store.demote(name, p.get("to", "cold"))
        return {"name": name, "tier": tier}

    def close_durables(self) -> None:
        """Flush and close every open durable document (their close()
        commits pending autocommit edits and releases the journal locks);
        serve() calls this on every exit path. Cold documents are
        already closed — their placeholder's close() is a no-op."""
        if self.store is not None:
            self.store.close()  # stop the eviction sweeper first
        with self._lock:
            self._durable_names.clear()
            self._handle_names.clear()
            durable = [
                (h, doc) for h, doc in self._docs.items()
                if hasattr(doc, "journal")
            ]
            for h, _ in durable:
                self._docs.pop(h, None)
        for _, doc in durable:
            try:
                doc.close()
            except Exception:
                pass  # shutdown must not die half-way through the list

    def fork(self, p):
        doc = self._doc(p)
        actor = bytes.fromhex(p["actor"]) if p.get("actor") else None
        heads = self._heads(p)
        forked = (
            doc.fork_at(heads, actor=ActorId(actor) if actor else None)
            if heads is not None
            else doc.fork(actor=ActorId(actor) if actor else None)
        )
        return {"doc": self._reg(self._docs, forked)}

    def actor(self, p):
        return self._doc(p).get_actor().bytes.hex()

    def heads(self, p):
        return [_b64(h) for h in self._doc(p).get_heads()]

    def docFence(self, p):
        """Affinity-matched no-op: routed through the document's shard
        queue like any other ``doc`` request, so its response proves
        every frame pipelined ahead of it has fully executed (the
        router's migration fence). Deliberately does NOT touch the
        document — fencing a cold doc must not hydrate it."""
        if p.get("doc") not in self._docs:
            raise ValueError(f"invalid doc handle {p.get('doc')}")
        return None

    def commit(self, p):
        h = self._doc(p).commit(message=p.get("message"))
        return _b64(h) if h is not None else None

    def save(self, p):
        return _b64(self._doc(p).save())

    def saveIncremental(self, p):
        return _b64(self._doc(p).save_incremental_after(self._heads(p) or []))

    def applyChanges(self, p):
        self._doc(p).load_incremental(_unb64(p["data"]), on_partial="error")
        return None

    def merge(self, p):
        # the merge source may be cold too: hydrate it like the target
        other = self._ensure_resident(p["other"])
        if other is None:
            raise ValueError(f"invalid doc handle {p.get('other')}")
        return [_b64(h) for h in self._doc(p).merge(other)]

    # mutation
    def put(self, p):
        self._doc(p).put(p["obj"], p["prop"], _to_scalar(p["value"]))
        return None

    def putObject(self, p):
        exid = self._doc(p).put_object(p["obj"], p["prop"], _OBJTYPES[p["type"]])
        return {"$obj": exid, "type": p["type"]}

    def insert(self, p):
        self._doc(p).insert(p["obj"], p["index"], _to_scalar(p["value"]))
        return None

    def insertObject(self, p):
        exid = self._doc(p).insert_object(p["obj"], p["index"], _OBJTYPES[p["type"]])
        return {"$obj": exid, "type": p["type"]}

    def delete(self, p):
        self._doc(p).delete(p["obj"], p.get("prop", p.get("index")))
        return None

    def increment(self, p):
        self._doc(p).increment(p["obj"], p.get("prop", p.get("index")), p["by"])
        return None

    def spliceText(self, p):
        self._doc(p).splice_text(p["obj"], p["pos"], p.get("del", 0), p.get("text", ""))
        return None

    def mark(self, p):
        self._doc(p).mark(
            p["obj"], p["start"], p["end"], p["name"], p["value"],
            expand=p.get("expand", "after"),
        )
        return None

    def unmark(self, p):
        self._doc(p).unmark(p["obj"], p["start"], p["end"], p["name"])
        return None

    # reads (all honor optional historical heads)
    def get(self, p):
        doc = self._doc(p)
        got = doc.get(p["obj"], p.get("prop", p.get("index")), heads=self._heads(p))
        return None if got is None else _from_rendered(got[0], got[1], doc)

    def getAll(self, p):
        doc = self._doc(p)
        return [
            _from_rendered(r, e, doc)
            for r, e in doc.get_all(p["obj"], p.get("prop", p.get("index")),
                                    heads=self._heads(p))
        ]

    def keys(self, p):
        return self._doc(p).keys(p["obj"], heads=self._heads(p))

    def length(self, p):
        return self._doc(p).length(p["obj"], heads=self._heads(p))

    def text(self, p):
        return self._doc(p).text(p["obj"], heads=self._heads(p))

    def marks(self, p):
        return [
            {"start": m.start, "end": m.end, "name": m.name, "value": m.value}
            for m in self._doc(p).marks(p["obj"], heads=self._heads(p))
        ]

    def getCursor(self, p):
        return self._doc(p).get_cursor(p["obj"], p["pos"], heads=self._heads(p))

    def getCursorPosition(self, p):
        return self._doc(p).get_cursor_position(
            p["obj"], p["cursor"], heads=self._heads(p)
        )

    def materialize(self, p):
        """Plain-JSON projection of the (sub)tree, like the wasm module's
        materialize: counters and timestamps flatten to numbers (JSON has
        no such types; ``get``/``getAll`` are the typed surface), bytes
        serialize as the {"$bytes"} wrapper."""
        return self._doc(p).hydrate(p.get("obj", "_root"), heads=self._heads(p))

    # patches
    def popPatches(self, p):
        """Patches since the previous pop — local AND remote changes, via
        the autocommit diff cursor (reference: autocommit.rs
        diff_incremental; the wasm popPatches surfaces local edits too).
        The first call pins the cursor at the current heads and returns
        an empty list."""
        doc = self._doc(p)
        if p["doc"] not in self._patched:
            self._patched.add(p["doc"])
            doc.update_diff_cursor(commit=False)
            return []
        # commit=False: popping must never close an open transaction (a
        # later explicit commit keeps its message); pending ops' patches
        # arrive on the pop after that commit
        return [self._patch_json(x) for x in doc.diff_incremental(commit=False)]

    @staticmethod
    def _patch_json(patch) -> dict:
        a = patch.action
        d = {"obj": patch.obj, "path": [list(pe) for pe in patch.path],
             "action": type(a).__name__}
        for f in getattr(a, "__dataclass_fields__", {}):
            v = getattr(a, f)
            if f == "marks":
                v = [
                    {"start": m.start, "end": m.end, "name": m.name,
                     "value": m.value}
                    for m in v
                ]
            d[f] = v
        return d

    # sync
    def syncStateNew(self, p):
        return {"sync": self._reg(self._syncs, SyncState())}

    def syncStateFree(self, p):
        self._syncs.pop(p["sync"], None)
        return None

    def syncStateEncode(self, p):
        return _b64(self._syncs[p["sync"]].encode())

    def syncStateDecode(self, p):
        return {"sync": self._reg(self._syncs, SyncState.decode(_unb64(p["data"])))}

    def generateSyncMessage(self, p):
        msg = self._doc(p).generate_sync_message(self._syncs[p["sync"]])
        return None if msg is None else _b64(msg.encode())

    def receiveSyncMessage(self, p):
        from .sync.protocol import Message

        doc = self._doc(p)
        msg = Message.decode(_unb64(p["data"]))
        doc.receive_sync_message(self._syncs[p["sync"]], msg)
        # a durable doc opened with device=true carries a resident
        # DeviceDoc: feed it incrementally so device reads stay current
        # (the serving layer coalesces runs of these into apply_batches)
        dev = getattr(doc, "device_doc", None)
        if dev is not None and msg.changes:
            try:
                dev.apply_changes(msg.changes)
            except Exception as e:  # noqa: BLE001 — isolate the sidecar
                obs.count("sync.device_feed_error", error=str(e)[:200])
        return None

    # resilient sync sessions (retry/backoff/reset over lossy transports;
    # see sync/session.py). The base retransmit timeout is the server's
    # syncTimeoutMs (``configure``), overridable per session.
    def _session_config(self, p) -> SessionConfig:
        timeout_ms = int(p.get("timeoutMs", self.sync_timeout_ms))
        if timeout_ms <= 0:
            raise ValueError("timeoutMs must be positive")
        timeout_s = timeout_ms / 1000.0
        return SessionConfig(
            timeout=timeout_s,
            max_timeout=timeout_s * 16,
            seed=int(p.get("seed", 0)),
        )

    def syncSessionNew(self, p):
        doc = self._doc(p)
        sess = SyncSession(
            doc,
            config=self._session_config(p),
            epoch=int(p.get("epoch", 1)),
            device_doc=getattr(doc, "device_doc", None),
        )
        h = self._reg(self._sessions, sess)
        self._session_docs[h] = p["doc"]
        return {"session": h}

    def syncSessionRestore(self, p):
        """Rebuild a session from persisted bytes after a restart; pass an
        epoch different from the pre-restart one."""
        doc = self._doc(p)
        sess = SyncSession.restore(
            doc,
            _unb64(p["data"]),
            epoch=int(p["epoch"]),
            config=self._session_config(p),
        )
        sess.device_doc = getattr(doc, "device_doc", None)
        h = self._reg(self._sessions, sess)
        self._session_docs[h] = p["doc"]
        return {"session": h}

    def syncSessionAttach(self, p):
        """Durable named session: restore (or create) the sync session
        for ``peer`` from the document's journal meta, with the epoch
        bumped — after a server restart or a failover promotion the
        surviving client session sees the new epoch and renegotiates
        through the epoch/reset handshake instead of a full resync.
        Re-attaching a peer that is already live returns the existing
        handle (the epoch only bumps across process incarnations)."""
        doc = self._durable_doc(p)
        peer = p.get("peer")
        if not isinstance(peer, str) or not peer:
            raise ValueError("syncSessionAttach requires a peer name")
        with self._lock:
            h = self._attached_sessions.get((p["doc"], peer))
            if h is not None and h in self._sessions:
                sess = self._sessions[h]
                return {"session": h, "epoch": sess.epoch}
        sess = doc.restore_sync_session(
            peer, config=self._session_config(p))
        h = self._reg(self._sessions, sess)
        with self._lock:
            self._session_docs[h] = p["doc"]
            self._attached_sessions[(p["doc"], peer)] = h
        return {"session": h, "epoch": sess.epoch}

    def _session(self, p) -> SyncSession:
        sess = self._sessions.get(p.get("session"))
        if sess is None:
            raise ValueError(f"invalid session handle {p.get('session')}")
        return sess

    def syncSessionPoll(self, p):
        frame = self._session(p).poll(time.monotonic())
        return None if frame is None else _b64(frame)

    def syncSessionReceive(self, p):
        """Feed wire bytes; corrupt or duplicate frames are absorbed (and
        counted), never raised."""
        accepted = self._session(p).receive(_unb64(p["data"]), time.monotonic())
        return {"accepted": accepted}

    def syncSessionStats(self, p):
        sess = self._session(p)
        return dict(sess.stats, converged=sess.converged(), epoch=sess.epoch)

    def syncSessionEncode(self, p):
        return _b64(self._session(p).encode())

    def syncSessionFree(self, p):
        with self._lock:
            self._sessions.pop(p.get("session"), None)
            self._session_docs.pop(p.get("session"), None)
            self._attached_sessions = {
                k: h for k, h in self._attached_sessions.items()
                if h != p.get("session")
            }
        return None

    # -- observability ------------------------------------------------------

    def metrics(self, p):
        """Metrics exposition for a live server. Default is Prometheus
        text (``{"method": "metrics"}`` -> ``result.body``); ``{"format":
        "json"}`` returns the structured snapshot plus the legacy
        counter/timing views."""
        fmt = p.get("format", "prometheus")
        if fmt == "prometheus":
            return {"format": "prometheus", "body": obs.render_prometheus()}
        if fmt == "json":
            with obs.registry.lock:
                counters = dict(obs.legacy_counters)
            return {
                "format": "json",
                "metrics": obs.snapshot(),
                "counters": counters,
                "timings": obs.timing_summary(),
            }
        raise ValueError(f"unknown metrics format {fmt!r}")

    def perfStatus(self, p):
        """The drain-cycle performance observatory's merged report
        (obs/prof.py): cumulative per-stage attribution with a
        host-vs-device split, batch occupancy, docs-per-launch,
        drain-cycle and queue-wait percentiles, and the bounded top-K
        expensive-docs table. ``{"top": n}`` sizes the doc table."""
        from .obs import prof

        top = p.get("top")
        return prof.profiler.status(top=int(top) if top is not None else None)

    def profileStart(self, p):
        """Start a ``jax.profiler`` device-trace capture with named
        annotations on every kernel-launch site; ``{"dir": path}``
        overrides the capture directory (default: a fresh temp dir,
        named in the response). Degrades cleanly where the profiler
        backend is unavailable: the answer is ``{"ok": false, "reason":
        ...}``, never an error (the ``enable_mesh`` contract)."""
        from .obs import prof

        return prof.jax_profile_start(p.get("dir"))

    def profileStop(self, p):
        """Stop the active ``jax.profiler`` capture; the response names
        the trace directory."""
        from .obs import prof

        return prof.jax_profile_stop()

    def heatStatus(self, p):
        """The doc-heat table (obs/heat.py): ranked per-document
        read/write/sync/bytes/drain rates. ``{"top": n}`` bounds the
        entry list. Scraping also refreshes the ``doc.heat`` gauges."""
        top = p.get("top")
        _heat.table.publish_gauges()
        return _heat.snapshot(top=int(top) if top is not None else None)

    def historyStatus(self, p):
        """The history rings (obs/history.py): downsampled trend slots
        per allowlisted metric family. ``{"name": fam}`` filters to one
        family, ``{"tier": 0|1|2}`` to one resolution tier."""
        from .obs import history

        tier = p.get("tier")
        return history.status(
            name=p.get("name"),
            tier=int(tier) if tier is not None else None)

    # -- dispatch -----------------------------------------------------------

    # explicit allowlist: getattr dispatch must never reach serve/handle or
    # any other non-API callable
    METHODS = frozenset({
        "create", "load", "free", "fork", "actor", "heads", "commit",
        "save", "saveIncremental", "applyChanges", "merge",
        "put", "putObject", "insert", "insertObject", "delete", "increment",
        "spliceText", "mark", "unmark",
        "get", "getAll", "keys", "length", "text", "marks",
        "getCursor", "getCursorPosition", "materialize", "popPatches",
        "syncStateNew", "syncStateFree", "syncStateEncode",
        "syncStateDecode", "generateSyncMessage", "receiveSyncMessage",
        "configure",
        "syncSessionNew", "syncSessionRestore", "syncSessionPoll",
        "syncSessionReceive", "syncSessionStats", "syncSessionEncode",
        "syncSessionFree", "syncSessionAttach",
        "openDurable", "durableCompact", "durableInfo", "durableReopen",
        "chaosDisk", "docDigest", "scrubNow",
        "storeStatus", "storeDemote", "docFence",
        "metrics", "perfStatus", "profileStart", "profileStop",
        "heatStatus", "historyStatus",
    })

    # heat-kind classification for the dispatch hook: which methods
    # count as read / write / sync load against their target document.
    # Fixed at class scope so the per-request cost is one dict lookup.
    _HEAT_KINDS = {
        **dict.fromkeys(
            ("put", "putObject", "insert", "insertObject", "delete",
             "increment", "spliceText", "mark", "unmark", "commit",
             "applyChanges", "merge"), "write"),
        **dict.fromkeys(
            ("get", "getAll", "keys", "length", "text", "marks",
             "getCursor", "getCursorPosition", "materialize",
             "popPatches", "heads", "save", "saveIncremental"), "read"),
        **dict.fromkeys(
            ("generateSyncMessage", "receiveSyncMessage",
             "syncSessionPoll", "syncSessionReceive",
             "syncSessionAttach"), "sync"),
    }

    def _note_heat(self, kind: str, p: dict) -> None:
        """Attribute one request (and its payload bytes) to its target
        document's heat entry. Only NAMED durable documents are
        tracked — the advisor reasons about placeable docs; anonymous
        handles have nothing to place. Never raises: load accounting
        must not be able to fail a request."""
        try:
            name = None
            h = p.get("doc")
            if h is None:
                s = p.get("session")
                if s is not None:
                    h = self._session_docs.get(s)
            if h is not None:
                name = self._handle_names.get(h)
            elif isinstance(p.get("name"), str):
                name = p["name"]
            if not name:
                return
            _heat.note(name, kind)
            nb = 0
            m = p.get("message")
            if isinstance(m, str):
                nb += len(m)
            d = p.get("data")
            if isinstance(d, str):
                nb += len(d)
            if nb:
                _heat.note(name, "bytes", nb)
        except Exception:  # noqa: BLE001
            pass

    def handle(self, req: dict) -> dict:
        rid = req.get("id")
        method = req.get("method", "")
        # the isinstance guard keeps unhashable method values (lists,
        # dicts) from raising out of the membership test
        if not isinstance(method, str) or method not in self.METHODS:
            # "unknown" keeps the method label bounded by the allowlist
            # (+1) no matter what a hostile client sends
            obs.count("rpc.errors",
                      labels={"method": "unknown", "type": "UnknownMethod"})
            return {"id": rid, "error": {"type": "UnknownMethod",
                                         "message": str(method),
                                         "retriable": False}}
        # last deadline gate: in the concurrent server this runs inside
        # the ack scope, just before the mutation would join the fsync
        # batch — the final point where an expired request can still be
        # refused without having executed anything
        if self.deadlines_enabled and request_expired(req):
            return deadline_response(rid, method, "pre_fsync")
        # optional cross-process trace context: {"trace": {"t": <trace
        # id>, "s": <parent span id>}} on the request parents this
        # process's spans into the caller's chain (router -> node, client
        # -> anything). Absent (the common case) this is one dict lookup;
        # malformed values deactivate the scope instead of erroring.
        tr = req.get("trace")
        if isinstance(tr, dict):
            with obs.trace_scope(tr.get("t"), tr.get("s")):
                return self._dispatch(rid, method, req)
        return self._dispatch(rid, method, req)

    def _dispatch(self, rid, method: str, req: dict) -> dict:
        if _heat.table.enabled:
            kind = self._HEAT_KINDS.get(method)
            if kind is not None:
                self._note_heat(kind, req.get("params") or {})
        # the span doubles as the per-method request counter (histogram
        # count) and latency distribution (rpc.request{method=...})
        with obs.span("rpc.request", labels={"method": method}):
            try:
                return {"id": rid,
                        "result": getattr(self, method)(req.get("params") or {})}
            except Exception as e:  # errors answer the request, never kill us
                obs.count("rpc.errors", labels={"method": method,
                                                "type": type(e).__name__})
                err = {"type": type(e).__name__, "message": str(e)}
                # every error answer carries an EXPLICIT retriable flag:
                # exceptions that know their retry semantics (a poisoned
                # journal, a replication-gate timeout) surface it; every
                # other exception is explicitly non-retriable, so clients
                # never have to guess from the type name
                retriable = getattr(e, "retriable", None)
                err["retriable"] = (
                    bool(retriable) if retriable is not None else False)
                # a shedding node's backoff hint (Overloaded) rides along
                ra = getattr(e, "retry_after_ms", None)
                if ra is not None:
                    err["retryAfterMs"] = int(ra)
                return {"id": rid, "error": err}

    @staticmethod
    def _json_default(v):
        # stray raw bytes (mark values, hydrated bytes scalars, patch
        # payloads) serialize as the documented wrapper instead of killing
        # the server
        if isinstance(v, (bytes, bytearray)):
            return {"$bytes": _b64(bytes(v))}
        raise TypeError(f"unserializable value of type {type(v).__name__}")

    def _encode_response(self, resp: dict) -> str:
        try:
            return json.dumps(resp, default=self._json_default)
        except Exception as e:
            return json.dumps({
                "id": resp.get("id"),
                "error": {"type": "EncodeError", "message": str(e),
                          "retriable": False},
            })

    def _parse_line(self, line: str) -> tuple[Optional[dict], Optional[dict]]:
        """One request line -> (request dict, early error response); at
        most one is non-None (both None for a blank line). The byte-limit
        and JSON-shape checks shared by the stdio loop and the socket
        transport (serve/server.py)."""
        line = line.strip()
        if not line:
            return None, None
        # measure encoded BYTES, not characters: a non-ASCII payload can be
        # 4x its character count (the ascii fast path avoids re-encoding)
        nbytes = (
            len(line) if line.isascii()
            else len(line.encode("utf-8", errors="surrogatepass"))
        )
        obs.count("rpc.bytes_in", n=nbytes)
        obs.observe("rpc.request_bytes", nbytes)
        if nbytes > self.max_request_bytes:
            obs.count("rpc.errors", labels={"method": "unknown",
                                            "type": "RequestTooLarge"})
            return None, {"id": None, "error": {
                "type": "RequestTooLarge",
                "message": f"request of {nbytes} bytes exceeds limit "
                           f"of {self.max_request_bytes}",
                "retriable": False}}
        try:
            req = json.loads(line)
        except json.JSONDecodeError as e:
            obs.count("rpc.errors", labels={"method": "unknown",
                                            "type": "ParseError"})
            return None, {"id": None,
                          "error": {"type": "ParseError", "message": str(e),
                                    "retriable": False}}
        if not isinstance(req, dict):
            obs.count("rpc.errors", labels={"method": "unknown",
                                            "type": "ParseError"})
            return None, {"id": None, "error": {
                "type": "ParseError",
                "message": "request must be a JSON object",
                "retriable": False}}
        # deadline propagation: an optional top-level ``deadlineMs``
        # (remaining budget at send time, like ``trace``) is stamped to
        # an absolute LOCAL expiry here — every later enforcement stage
        # (admission, dequeue, pre-fsync) compares against the same
        # monotonic clock, immune to cross-host clock skew
        dl = req.get("deadlineMs")
        if (isinstance(dl, (int, float)) and not isinstance(dl, bool)
                and dl > 0):
            req["_deadline_ts"] = obs.now() + float(dl) / 1000.0
        return req, None

    def _handle_line(self, line: str) -> tuple[Optional[dict], bool]:
        """One request line -> (response dict or None, stop flag).
        Total error isolation: any malformed frame becomes an ``error``
        response; nothing a client sends can raise out of here."""
        req, early = self._parse_line(line)
        if early is not None:
            return early, False
        if req is None:
            return None, False
        if req.get("method") == "shutdown":
            return {"id": req.get("id"), "result": None}, True
        try:
            return self.handle(req), False
        except Exception as e:  # belt and braces: handle() already catches
            retriable = getattr(e, "retriable", None)
            return {"id": None,
                    "error": {"type": type(e).__name__,
                              "message": str(e),
                              "retriable": bool(retriable)
                              if retriable is not None else False}}, False

    def serve(self, stdin=None, stdout=None) -> None:
        stdin = stdin or sys.stdin
        stdout = stdout or sys.stdout
        raw_readline = getattr(stdin, "readline", None)
        if raw_readline is None:  # plain iterables of lines work too
            it = iter(stdin)
            readline = lambda: next(it, "")  # noqa: E731
        else:
            def readline():
                # bounded read: a request longer than the limit is never
                # buffered whole — the tail is drained (and discarded) in
                # limit-sized chunks until its newline, then rejected.
                # readline(limit) counts characters, so the true buffer
                # bound is limit..4*limit bytes; _handle_line then enforces
                # the byte-exact limit on what survives
                limit = self.max_request_bytes + 1
                line = raw_readline(limit)
                if len(line) >= limit and not line.endswith("\n"):
                    while True:
                        tail = raw_readline(limit)
                        if not tail or tail.endswith("\n"):
                            break
                return line
        try:
            while True:
                try:
                    line = readline()
                except Exception as e:
                    # broken pipe / undecodable stream: clean shutdown —
                    # but a VISIBLE one; a silently dropped client is
                    # indistinguishable from a healthy idle one in metrics
                    obs.count("rpc.errors", labels={"method": "transport",
                                                    "type": "transport"})
                    obs.event("rpc.transport_death", stage="read",
                              error=str(e))
                    return
                if not line:  # EOF (including mid-request cut-offs)
                    return
                resp, stop = self._handle_line(line)
                if resp is not None:
                    payload = self._encode_response(resp) + "\n"
                    obs.count("rpc.bytes_out", n=len(payload))
                    try:
                        stdout.write(payload)
                        stdout.flush()
                    except Exception as e:
                        # client went away mid-response: shutdown, counted
                        obs.count("rpc.errors",
                                  labels={"method": "transport",
                                          "type": "transport"})
                        obs.event("rpc.transport_death", stage="write",
                                  error=str(e))
                        return
                if stop:
                    return
        finally:
            # every exit path flushes durable docs: a client that vanishes
            # without free() must not strand a pending autocommit tx (or
            # the journal flocks) any more than a clean shutdown would
            self.close_durables()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="automerge_tpu.rpc",
        description="line-delimited JSON-RPC frontend over stdio or sockets",
    )
    ap.add_argument(
        "--durable", metavar="DIR", default=None,
        help="persist named documents (openDurable) as crash-safe "
             "journal+snapshot directories under DIR",
    )
    ap.add_argument(
        "--socket", metavar="HOST:PORT", default=None,
        help="serve concurrently over TCP instead of stdio (port 0 picks "
             "a free port; the bound address prints to stderr)",
    )
    ap.add_argument(
        "--unix", metavar="PATH", default=None,
        help="serve concurrently over a unix-domain socket at PATH",
    )
    ap.add_argument(
        "--workers", type=int, default=None,
        help="worker pool size for socket mode "
             "(default AUTOMERGE_TPU_SERVE_WORKERS or 8)",
    )
    ap.add_argument(
        "--node-id", default=None, metavar="ID",
        help="run as a cluster node (cluster/node.py) with this id; "
             "requires --socket and --durable",
    )
    ap.add_argument(
        "--replicate-to", action="append", default=[], metavar="HOST:PORT",
        help="cluster leader: ship acked journal records to this "
             "follower node (repeatable)",
    )
    ap.add_argument(
        "--follow", default=None, metavar="HOST:PORT",
        help="cluster follower: reject client mutations (NotLeader, "
             "naming this leader) and accept the replication stream",
    )
    ap.add_argument(
        "--ack-replicas", type=int, default=None,
        help="cluster leader: client acks wait until this many "
             "followers hold the write durably (default "
             "AUTOMERGE_TPU_CLUSTER_ACK_REPLICAS or 0)",
    )
    ap.add_argument(
        "--flight-dir", metavar="DIR", default=None,
        help="dump the flight recorder (recent spans/events/metric "
             "deltas) to DIR on exit/crash (default "
             "AUTOMERGE_TPU_FLIGHT_DIR; merge dumps with "
             "`python -m automerge_tpu flight-merge`)",
    )
    args = ap.parse_args(argv)
    flight_dir = args.flight_dir or os.environ.get("AUTOMERGE_TPU_FLIGHT_DIR")
    if flight_dir:
        obs.flight.install(
            flight_dir, node_id=args.node_id or f"rpc-{os.getpid()}")
    if args.durable:
        os.makedirs(args.durable, exist_ok=True)
    if args.socket or args.unix:
        import signal

        from .serve import SocketRpcServer

        # a DEDICATED server process trades single-thread switch latency
        # for cross-thread fairness: the default 5ms GIL switch interval
        # lets one busy conn thread starve the worker pool for whole
        # request lifetimes (observed: >2x tail-latency inflation)
        sys.setswitchinterval(float(
            os.environ.get("AUTOMERGE_TPU_SERVE_SWITCH_INTERVAL", "0.001")
        ))

        cluster = bool(args.node_id or args.replicate_to or args.follow)
        if cluster:
            from .cluster import ClusterNode

            if not (args.socket and args.durable):
                print("cluster node mode requires --socket and --durable",
                      file=sys.stderr)
                return 2
            host, _, port = args.socket.rpartition(":")
            srv = ClusterNode(
                node_id=args.node_id or f"node-{os.getpid()}",
                host=host or "127.0.0.1", port=int(port),
                durable_dir=args.durable,
                role="follower" if args.follow else "leader",
                leader_addr=args.follow,
                replicate_to=args.replicate_to,
                ack_replicas=args.ack_replicas,
                workers=args.workers,
            )
        elif args.socket:
            host, _, port = args.socket.rpartition(":")
            srv = SocketRpcServer(
                host=host or "127.0.0.1", port=int(port),
                workers=args.workers, durable_dir=args.durable,
            )
        else:
            srv = SocketRpcServer(
                unix_path=args.unix, workers=args.workers,
                durable_dir=args.durable,
            )
        srv.start()
        print(f"serving on {srv.address}", file=sys.stderr, flush=True)
        for sig in (signal.SIGINT, signal.SIGTERM):
            signal.signal(sig, lambda *_: srv._shutdown.set())
        srv.serve_forever()
        return 0
    RpcServer(durable_dir=args.durable).serve()
    return 0


if __name__ == "__main__":
    sys.exit(main())
