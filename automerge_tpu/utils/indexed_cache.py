"""Interning cache: value <-> dense index, insertion-ordered.

Mirrors the reference's IndexedCache (reference:
rust/automerge/src/indexed_cache.rs) plus a byte-rank table used by the
columnar layers: Lamport ties break on actor *bytes*, so device kernels need
an index->rank permutation that sorts identically to the raw bytes.
"""

from __future__ import annotations

from typing import Dict, Generic, List, Optional, TypeVar

T = TypeVar("T")


class IndexedCache(Generic[T]):
    __slots__ = ("items", "_lookup", "_ranks", "_ranks_dirty")

    def __init__(self):
        self.items: List[T] = []
        self._lookup: Dict[T, int] = {}
        self._ranks: List[int] = []
        self._ranks_dirty = False

    def cache(self, value: T) -> int:
        idx = self._lookup.get(value)
        if idx is None:
            idx = len(self.items)
            self.items.append(value)
            self._lookup[value] = idx
            self._ranks_dirty = True
        return idx

    def lookup(self, value: T) -> Optional[int]:
        return self._lookup.get(value)

    def get(self, idx: int) -> T:
        return self.items[idx]

    def safe_get(self, idx: int) -> Optional[T]:
        if 0 <= idx < len(self.items):
            return self.items[idx]
        return None

    def remove_last(self) -> T:
        value = self.items.pop()
        del self._lookup[value]
        self._ranks_dirty = True
        return value

    def __len__(self) -> int:
        return len(self.items)

    def __contains__(self, value: T) -> bool:
        return value in self._lookup

    def __iter__(self):
        return iter(self.items)

    def ranks(self) -> List[int]:
        """rank[i] = position of item i in sorted order of the items.

        Used so (counter, rank[actor_idx]) sorts identically to
        (counter, actor bytes) in packed integer keys on device.
        """
        if self._ranks_dirty or len(self._ranks) != len(self.items):
            order = sorted(range(len(self.items)), key=lambda i: self.items[i])
            self._ranks = [0] * len(self.items)
            for rank, i in enumerate(order):
                self._ranks[i] = rank
            self._ranks_dirty = False
        return self._ranks

    def sorted_order(self) -> List[int]:
        """Indices of items in sorted order (the save-time actor permutation)."""
        return sorted(range(len(self.items)), key=lambda i: self.items[i])

    def copy(self) -> "IndexedCache[T]":
        c = IndexedCache()
        c.items = list(self.items)
        c._lookup = dict(self._lookup)
        c._ranks = list(self._ranks)
        c._ranks_dirty = self._ranks_dirty
        return c
