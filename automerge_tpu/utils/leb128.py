"""LEB128 variable-length integer codecs.

Byte-compatible with the reference encoding (reference:
rust/automerge/src/columnar/encoding/encodable_impls.rs:134-200 and
rust/automerge/src/storage/parse/leb128.rs). Unsigned values use ULEB128,
signed values use SLEB128 (two's complement, sign-extended).
"""

from __future__ import annotations


from ..errors import AutomergeError


class LEBDecodeError(AutomergeError):
    pass


def encode_uleb(value: int, out: bytearray) -> int:
    """Append the ULEB128 encoding of ``value`` to ``out``; return bytes written."""
    if value < 0:
        raise ValueError(f"cannot uleb-encode negative value {value}")
    n = 0
    while True:
        byte = value & 0x7F
        value >>= 7
        if value:
            out.append(byte | 0x80)
            n += 1
        else:
            out.append(byte)
            return n + 1


def encode_sleb(value: int, out: bytearray) -> int:
    """Append the SLEB128 encoding of ``value`` to ``out``; return bytes written."""
    n = 0
    while True:
        byte = value & 0x7F
        # Arithmetic shift: Python ints shift preserves sign for negatives.
        value >>= 7
        sign_bit = byte & 0x40
        done = (value == 0 and not sign_bit) or (value == -1 and sign_bit)
        if done:
            out.append(byte)
            return n + 1
        out.append(byte | 0x80)
        n += 1


def uleb_bytes(value: int) -> bytes:
    buf = bytearray()
    encode_uleb(value, buf)
    return bytes(buf)


def sleb_bytes(value: int) -> bytes:
    buf = bytearray()
    encode_sleb(value, buf)
    return bytes(buf)


def ulebsize(value: int) -> int:
    """Number of bytes ULEB128 encoding of ``value`` occupies.

    Mirrors reference rust/automerge/src/columnar/encoding/leb128.rs.
    """
    if value == 0:
        return 1
    n = 0
    while value:
        value >>= 7
        n += 1
    return n


def lebsize(value: int) -> int:
    """Number of bytes SLEB128 encoding of ``value`` occupies."""
    if value >= 0:
        bits = value.bit_length() + 1  # +1 for sign bit
    else:
        bits = (~value).bit_length() + 1
    return (bits + 6) // 7


def decode_uleb(buf, pos: int) -> tuple[int, int]:
    """Decode a ULEB128 value from ``buf`` at ``pos``.

    Returns (value, new_pos). Rejects truncated input, values exceeding u64,
    and overlong encodings (trailing zero continuation byte) — matching the
    reference's strict parser (storage/parse/leb128.rs).
    """
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise LEBDecodeError("uleb: unexpected end of input")
        byte = buf[pos]
        pos += 1
        result |= (byte & 0x7F) << shift
        shift += 7
        if not (byte & 0x80):
            if shift > 64 and byte > 1:
                raise LEBDecodeError("uleb: value out of u64 range")
            if shift > 7 and byte == 0:
                raise LEBDecodeError("uleb: overlong encoding")
            return result, pos
        if shift > 64:
            raise LEBDecodeError("uleb: value out of u64 range")


def decode_sleb(buf, pos: int) -> tuple[int, int]:
    """Decode an SLEB128 value from ``buf`` at ``pos``. Returns (value, new_pos).

    Rejects truncation, values outside i64, and overlong encodings (a final
    byte that only repeats the penultimate byte's sign bit).
    """
    result = 0
    shift = 0
    prev = 0
    while True:
        if pos >= len(buf):
            raise LEBDecodeError("sleb: unexpected end of input")
        byte = buf[pos]
        pos += 1
        result |= (byte & 0x7F) << shift
        shift += 7
        if not (byte & 0x80):
            if shift > 64 and byte != 0 and byte != 0x7F:
                raise LEBDecodeError("sleb: value out of i64 range")
            if shift > 7 and (
                (byte == 0 and not (prev & 0x40)) or (byte == 0x7F and prev & 0x40)
            ):
                raise LEBDecodeError("sleb: overlong encoding")
            if byte & 0x40:
                result -= 1 << shift
            # Wrap to i64 two's complement range like the reference's i64.
            result &= (1 << 64) - 1
            if result >= 1 << 63:
                result -= 1 << 64
            return result, pos
        if shift > 64:
            raise LEBDecodeError("sleb: value out of i64 range")
        prev = byte
