"""Range filtering shared by the host and device ReadDoc surfaces
(reference: read.rs map_range/list_range)."""

from __future__ import annotations

from typing import Optional


def filter_map_range(entries, start: Optional[str], end: Optional[str]):
    """(key, value, id) rows with start <= key < end."""
    out = []
    for key, val, vid in entries:
        if start is not None and key < start:
            continue
        if end is not None and key >= end:
            continue
        out.append((key, val, vid))
    return out
