"""Columnar codec primitives: RLE, delta-RLE, boolean run-length, raw values.

Byte-compatible with the reference's columnar encoding layer (reference:
rust/automerge/src/columnar/encoding/{rle.rs,delta.rs,boolean.rs}). The exact
run/literal/null-run state machine is mirrored because change hashes are
computed over these bytes — any drift breaks interop and head verification.

Wire format (per RLE column):
  - sleb n > 0: a run; followed by one value repeated n times
  - sleb n < 0: a literal run of |n| values
  - sleb 0:     a null run; followed by uleb count
A column that is entirely null encodes to zero bytes.

Value encodings within columns:
  - uint  -> ULEB128
  - int   -> SLEB128
  - str   -> ULEB128 byte length + UTF-8 bytes
"""

from __future__ import annotations

from .leb128 import decode_sleb, decode_uleb, encode_sleb, encode_uleb

_I64_MAX = (1 << 63) - 1
_I64_MIN = -(1 << 63)


def _sat_i64(v: int) -> int:
    """Saturate to i64 range (the reference uses saturating arithmetic)."""
    if v > _I64_MAX:
        return _I64_MAX
    if v < _I64_MIN:
        return _I64_MIN
    return v


def _encode_uint(value: int, out: bytearray) -> None:
    encode_uleb(value, out)


def _encode_int(value: int, out: bytearray) -> None:
    encode_sleb(value, out)


def _encode_str(value: str, out: bytearray) -> None:
    raw = value.encode("utf-8")
    encode_uleb(len(raw), out)
    out += raw


def _decode_uint(buf, pos):
    return decode_uleb(buf, pos)


def _decode_int(buf, pos):
    return decode_sleb(buf, pos)


def _decode_str(buf, pos):
    n, pos = decode_uleb(buf, pos)
    if pos + n > len(buf):
        raise ValueError("string column: truncated")
    return buf[pos : pos + n].decode("utf-8"), pos + n


# State tags for the RLE encoder
_EMPTY = 0
_INITIAL_NULLS = 1
_NULLS = 2
_LONE = 3
_RUN = 4
_LITERAL = 5


class RleEncoder:
    """Run-length encoder over optional values.

    ``kind`` is one of "uint", "int", "str" and selects the value codec.
    """

    def __init__(self, kind: str = "uint"):
        self.out = bytearray()
        if kind == "uint":
            self._enc = _encode_uint
        elif kind == "int":
            self._enc = _encode_int
        elif kind == "str":
            self._enc = _encode_str
        else:
            raise ValueError(f"unknown rle kind {kind!r}")
        self._state = _EMPTY
        self._value = None  # current run / lone value / last literal value
        self._count = 0  # run or null-run length
        self._lits: list = []  # accumulated literal run (excluding _value)

    def _flush_run(self, value, count: int) -> None:
        encode_sleb(count, self.out)
        self._enc(value, self.out)

    def _flush_nulls(self, count: int) -> None:
        encode_sleb(0, self.out)
        encode_uleb(count, self.out)

    def _flush_literals(self, values) -> None:
        encode_sleb(-len(values), self.out)
        for v in values:
            self._enc(v, self.out)

    def append(self, value) -> None:
        if value is None:
            self.append_null()
        else:
            self.append_value(value)

    def append_null(self) -> None:
        st = self._state
        if st == _EMPTY:
            self._state, self._count = _INITIAL_NULLS, 1
        elif st in (_INITIAL_NULLS, _NULLS):
            self._count += 1
        elif st == _LONE:
            self._flush_literals([self._value])
            self._state, self._count = _NULLS, 1
        elif st == _RUN:
            self._flush_run(self._value, self._count)
            self._state, self._count = _NULLS, 1
        elif st == _LITERAL:
            self._lits.append(self._value)
            self._flush_literals(self._lits)
            self._lits = []
            self._state, self._count = _NULLS, 1

    def append_null_run(self, n: int) -> None:
        """Append ``n`` nulls in O(1) (bulk columns with long null tails)."""
        if n <= 0:
            return
        self.append_null()
        if self._state in (_INITIAL_NULLS, _NULLS):
            self._count += n - 1

    def append_value_run(self, value, n: int) -> None:
        """Append ``n`` equal values in O(1) (bulk run-encoded columns)."""
        if n <= 0:
            return
        self.append_value(value)
        if n == 1:
            return
        self.append_value(value)  # any state + same value twice -> _RUN
        self._count += n - 2

    def append_value(self, value) -> None:
        st = self._state
        if st == _EMPTY:
            self._state, self._value = _LONE, value
        elif st == _LONE:
            if self._value == value:
                self._state, self._count = _RUN, 2
            else:
                self._lits = [self._value]
                self._value = value
                self._state = _LITERAL
        elif st == _RUN:
            if self._value == value:
                self._count += 1
            else:
                self._flush_run(self._value, self._count)
                self._state, self._value = _LONE, value
        elif st == _LITERAL:
            if self._value == value:
                self._flush_literals(self._lits)
                self._lits = []
                self._state, self._count = _RUN, 2
            else:
                self._lits.append(self._value)
                self._value = value
        else:  # null runs
            self._flush_nulls(self._count)
            self._state, self._value = _LONE, value

    def finish(self) -> bytes:
        st = self._state
        if st == _NULLS:
            self._flush_nulls(self._count)
        elif st == _LONE:
            self._flush_literals([self._value])
        elif st == _RUN:
            self._flush_run(self._value, self._count)
        elif st == _LITERAL:
            self._lits.append(self._value)
            self._flush_literals(self._lits)
        # _EMPTY and _INITIAL_NULLS emit nothing: an all-null column is empty.
        self._state = _EMPTY
        return bytes(self.out)


# Bound on values decoded from a column when the caller doesn't know the row
# count up front: a crafted 10-byte header must not demand a terabyte list.
MAX_COLUMN_VALUES = 1 << 24


def rle_decode(
    buf, kind: str = "uint", count: int | None = None, max_total: int = MAX_COLUMN_VALUES
) -> list:
    """Decode an RLE column into a list of optional values.

    If ``count`` is given, stop after that many values; runs are clamped to
    the remaining demand so attacker-controlled run lengths never materialize
    beyond it. Without ``count``, decoding is bounded by ``max_total``.
    """
    if kind == "uint":
        dec = _decode_uint
    elif kind == "int":
        dec = _decode_int
    elif kind == "str":
        dec = _decode_str
    else:
        raise ValueError(f"unknown rle kind {kind!r}")
    limit = count if count is not None else max_total
    out: list = []
    pos = 0
    n = len(buf)
    while pos < n and len(out) < limit:
        header, pos = decode_sleb(buf, pos)
        take = limit - len(out)
        if header > 0:
            value, pos = dec(buf, pos)
            out.extend([value] * min(header, take))
        elif header < 0:
            for _ in range(-header):
                value, pos = dec(buf, pos)
                if len(out) < limit:
                    out.append(value)
        else:
            nulls, pos = decode_uleb(buf, pos)
            out.extend([None] * min(nulls, take))
    if count is None and len(out) >= max_total and pos < n:
        raise ValueError("rle column demands too many values")
    return out


class DeltaEncoder:
    """RLE over successive differences; absolute values start at 0.

    Reference: rust/automerge/src/columnar/encoding/delta.rs.
    """

    def __init__(self):
        self._rle = RleEncoder("int")
        self._abs = 0

    def append(self, value) -> None:
        if value is None:
            self._rle.append_null()
        else:
            self._rle.append_value(_sat_i64(value - self._abs))
            self._abs = value

    def finish(self) -> bytes:
        return self._rle.finish()


def delta_decode(buf, count: int | None = None, max_total: int = MAX_COLUMN_VALUES) -> list:
    deltas = rle_decode(buf, "int", count, max_total)
    out: list = []
    absolute = 0
    for d in deltas:
        if d is None:
            out.append(None)
        else:
            absolute = _sat_i64(absolute + d)
            out.append(absolute)
    return out


class BooleanEncoder:
    """Alternating run lengths, starting with the count of ``False`` values.

    Reference: rust/automerge/src/columnar/encoding/boolean.rs.
    """

    def __init__(self):
        self.out = bytearray()
        self._last = False
        self._count = 0

    def append(self, value: bool) -> None:
        if value == self._last:
            self._count += 1
        else:
            encode_uleb(self._count, self.out)
            self._last = value
            self._count = 1

    def append_run(self, value: bool, n: int) -> None:
        """Append ``n`` equal values in O(1)."""
        if n <= 0:
            return
        self.append(value)
        self._count += n - 1

    def finish(self) -> bytes:
        if self._count > 0:
            encode_uleb(self._count, self.out)
        return bytes(self.out)


def boolean_decode(
    buf, count: int | None = None, max_total: int = MAX_COLUMN_VALUES
) -> list[bool]:
    limit = count if count is not None else max_total
    out: list[bool] = []
    pos = 0
    value = True
    while pos < len(buf) and len(out) < limit:
        run, pos = decode_uleb(buf, pos)
        value = not value
        out.extend([value] * min(run, limit - len(out)))
    if count is None and len(out) >= max_total and pos < len(buf):
        raise ValueError("boolean column demands too many values")
    if count is not None and len(out) < count:
        # Decoder yields False once input is exhausted.
        out.extend([False] * (count - len(out)))
    return out


class MaybeBooleanEncoder:
    """BooleanEncoder that emits zero bytes when every value is False.

    Reference: boolean.rs MaybeBooleanEncoder (used for expand columns).
    """

    def __init__(self):
        self._inner = BooleanEncoder()
        self._all_false = True

    def append(self, value: bool) -> None:
        if value:
            self._all_false = False
        self._inner.append(value)

    def append_run(self, value: bool, n: int) -> None:
        if value and n > 0:
            self._all_false = False
        self._inner.append_run(value, n)

    def finish(self) -> bytes:
        if self._all_false:
            return b""
        return self._inner.finish()


def _run_bounds(arr):
    """[(start, end)] of equal-value runs in ``arr``."""
    import numpy as np

    n = len(arr)
    if not n:
        return []
    b = np.flatnonzero(np.diff(arr)) + 1
    starts = np.concatenate([[0], b])
    ends = np.concatenate([b, [n]])
    return zip(starts.tolist(), ends.tolist())


def _str_runs_col(ids, table, enc) -> bytes:
    """Drive a string RleEncoder from an int-id column (-1 = null) using
    vectorized run boundaries + O(1) bulk appends."""
    for s, e in _run_bounds(ids):
        v = int(ids[s])
        if v < 0:
            enc.append_null_run(e - s)
        else:
            enc.append_value_run(table[v], e - s)
    return enc.finish()


def _bool_runs_col(vals, enc) -> bytes:
    for s, e in _run_bounds(vals):
        enc.append_run(bool(vals[s]), e - s)
    return enc.finish()
