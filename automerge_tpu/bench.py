"""Benchmark workload builders + the native sequential-apply baseline.

Workloads mirror the reference's benchmark surface (BASELINE.md configs;
reference harnesses: rust/edit-trace/src/main.rs, rust/automerge/benches/
{map,sync}.rs) at real scale:

  1. replay      — the full 259,778-op edit trace through the host
                   transaction layer (edit-trace/src/main.rs:23-55)
  2. fanin       — N genuinely divergent replicas of the trace document,
                   merged (automerge.rs:460,917 fork/merge)
  3. mapcounter  — many actors concurrently incrementing shared counters +
                   conflicting map puts (pure commutative merge)
  4. rga         — many actors interleaving insert/delete on one sequence
  5. sync        — two replicas with a large divergence catching up over
                   generate/receive_sync_message (sync.rs:25-68)

Replica changes are synthesized directly at the change level — each replica
gets a distinct actor, distinct anchor positions, and distinct payload
drawn from its own trace slice, with deps = the base heads. This is the
same byte format a real fork would commit (build_change recomputes columns
and hashes), without paying a full per-replica document replay.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .api import AutoDoc
from .storage.change import HEAD_STORED, ROOT_STORED, ChangeOp, Key, StoredChange, build_change
from .types import ActorId, ObjType, ScalarValue

TRACE_PATH = "/root/reference/rust/edit-trace/edits.json"

_ACTION_PUT = 1
_ACTION_DELETE = 3
_ACTION_INCREMENT = 5


def load_trace(limit: Optional[int] = None) -> list:
    """The canonical editing trace (or a deterministic synthetic fallback)."""
    if os.path.exists(TRACE_PATH):
        with open(TRACE_PATH) as f:
            edits = json.load(f)
        return edits[:limit] if limit else edits
    rng = np.random.default_rng(0)
    n = limit or 260_000
    edits, length = [], 0
    for _ in range(n):
        if length == 0 or rng.random() < 0.85:
            edits.append([int(rng.integers(0, length + 1)), 0, "x"])
            length += 1
        else:
            edits.append([int(rng.integers(0, length)), 1])
            length -= 1
    return edits


def apply_edits(doc: AutoDoc, text_obj: str, edits: Iterable) -> int:
    """Replay trace edits; returns the number of ops issued.

    Mirrors the reference replay loop (rust/edit-trace/src/main.rs:23-31):
    one splice_text call per edit, no per-edit length query — the length
    used for clamping synthetic traces is tracked arithmetically."""
    from .types import str_width

    n = 0
    ln = doc.length(text_obj)
    splice = doc.splice_text
    for e in edits:
        pos = e[0]
        if pos > ln:
            pos = ln
        ndel = e[1]
        if ndel > ln - pos:
            ndel = ln - pos
        text = "".join(e[2:])
        splice(text_obj, pos, ndel, text)
        w = str_width(text)
        ln += w - ndel
        n += ndel + len(text)
    return n


class BaseInfo:
    """Everything the synthesizers need to know about the base document."""

    def __init__(self, doc: AutoDoc, text_exid: str):
        d = doc.doc
        self.doc = doc
        self.text_exid = text_exid
        self.heads = d.get_heads()
        self.max_op = d.max_op
        self.changes = [a.stored for a in d.history]
        ctr_s, actor_hex = text_exid.split("@", 1)
        self.text_obj: Tuple[int, bytes] = (int(ctr_s), bytes.fromhex(actor_hex))
        # visible elements in document order as (counter, actor bytes)
        info = d.ops.get_obj(d.import_obj(text_exid))
        elems: List[Tuple[int, bytes]] = []
        for el in info.data.elements():
            if el.winner() is not None:
                eid = el.elem_id
                elems.append((eid[0], d.actors.get(eid[1]).bytes))
        self.elems = elems


def build_base(trace: Sequence, n_edits: int) -> BaseInfo:
    base = AutoDoc(actor=ActorId(bytes([1]) * 16))
    text = base.put_object("_root", "text", ObjType.TEXT)
    apply_edits(base, text, trace[:n_edits])
    base.commit()
    return BaseInfo(base, text)


def _replica_actor(i: int) -> bytes:
    return b"\x03" + i.to_bytes(3, "big") + bytes(12)


def synth_seq_change(
    base: BaseInfo,
    actor: bytes,
    edits: Sequence,
    seed: int,
) -> StoredChange:
    """One replica's divergent change against ``base``: trace-slice edits
    re-anchored onto the base document's element ids.

    Inserts chain off one another exactly as a replayed splice would
    (transaction/inner.rs:672-683); deletes pred the element's insert op
    (elements of a pure-splice doc are never overwritten). Anchors come
    from the slice's own positions, so every replica diverges genuinely.
    """
    rng = np.random.default_rng(seed)
    n_base = len(base.elems)
    # chunk-local actor table: author first, then referenced others sorted
    others = sorted(({a for _, a in base.elems} | {base.text_obj[1]}) - {actor})
    local = {actor: 0, **{a: i + 1 for i, a in enumerate(others)}}
    obj = (base.text_obj[0], local[base.text_obj[1]])

    ops: List[ChangeOp] = []
    ctr = base.max_op  # ids start at max_op + 1
    deleted: set = set()
    last_insert: Optional[Tuple[int, int]] = None
    last_insert_pos = -2
    for e in edits:
        pos = min(int(e[0]), max(n_base - 1, 0))
        text = "".join(e[2:])
        if e[1] and n_base:
            # delete a not-yet-deleted base element near the trace position
            k = pos
            for _ in range(8):
                if k not in deleted and k < n_base:
                    break
                k = int(rng.integers(0, n_base))
            if k in deleted or k >= n_base:
                continue
            deleted.add(k)
            ec, ea = base.elems[k]
            elem = (ec, local[ea])
            ctr += 1
            ops.append(
                ChangeOp(
                    obj=obj,
                    key=Key.seq(elem),
                    insert=False,
                    action=_ACTION_DELETE,
                    value=ScalarValue("null"),
                    pred=[elem],
                )
            )
        for ch in text:
            if last_insert is not None and pos == last_insert_pos + 1:
                elem = last_insert  # chain onto our own previous insert
            elif pos == 0 or n_base == 0:
                elem = HEAD_STORED
            else:
                ec, ea = base.elems[min(pos - 1, n_base - 1)]
                elem = (ec, local[ea])
            ctr += 1
            ops.append(
                ChangeOp(
                    obj=obj,
                    key=Key.seq(elem),
                    insert=True,
                    action=_ACTION_PUT,
                    value=ScalarValue("str", ch),
                )
            )
            last_insert = (ctr, 0)
            last_insert_pos = pos
            pos += 1
    return build_change(
        StoredChange(
            dependencies=list(base.heads),
            actor=actor,
            other_actors=others,
            seq=1,
            start_op=base.max_op + 1,
            timestamp=0,
            message=None,
            ops=ops,
        )
    )


def synth_fanin(
    base: BaseInfo, trace: Sequence, n_replicas: int, per_replica: int, offset: int
) -> List[StoredChange]:
    """Config 2: N divergent replicas, each replaying its own trace slice.

    Slices wrap within [offset/2, end) — the full-trace base leaves no
    tail, and the LATE trace is what carries real editing behavior
    (cursor jumps, deletes, spread positions). Early-trace slices are
    pure sequential typing whose inserts all chain locally, which would
    flatter every engine's fast path and measure nothing."""
    out = []
    lo0 = min(offset // 2, max(len(trace) - per_replica - 1, 0))
    span = max(len(trace) - lo0 - per_replica, 1)
    for i in range(n_replicas):
        lo = lo0 + (offset // 2 + i * per_replica) % span
        out.append(
            synth_seq_change(
                base, _replica_actor(i), trace[lo : lo + per_replica], seed=1000 + i
            )
        )
    return out


def synth_rga(
    base: BaseInfo, n_actors: int, ops_per_actor: int
) -> List[StoredChange]:
    """Config 4: interleaved insert/delete storms on one shared sequence."""
    out = []
    n_base = len(base.elems)
    for i in range(n_actors):
        rng = np.random.default_rng(7000 + i)
        edits = []
        for j in range(ops_per_actor):
            pos = int(rng.integers(0, max(n_base, 1)))
            if j % 3 == 2:
                edits.append([pos, 1])
            else:
                edits.append([pos, 0, chr(97 + (i + j) % 26)])
        out.append(synth_seq_change(base, _replica_actor(i), edits, seed=7000 + i))
    return out


def build_counter_base(n_counters: int) -> Tuple[AutoDoc, List[str]]:
    doc = AutoDoc(actor=ActorId(bytes([1]) * 16))
    keys = [f"c{j}" for j in range(n_counters)]
    for k in keys:
        doc.put("_root", k, ScalarValue("counter", 0))
    doc.commit()
    return doc, keys


def synth_mapcounter(
    doc: AutoDoc, keys: List[str], n_actors: int, incs_per_actor: int
) -> Tuple[List[StoredChange], Dict[str, int]]:
    """Config 3: many actors increment shared counters + conflicting puts.

    Increment preds name the counter put op (transaction.rs increment path);
    every replica also puts a few shared map keys so the merge resolves real
    conflicts, not just commutative adds. Returns (changes, expected
    per-key counter totals) so callers can verify the merge exactly.

    Changes are built straight at the column level (the array-native
    ``build_change(cols=...)`` path also used by document load) — one
    replica's whole op block is numpy arrays, never ChangeOp objects, so
    synthesizing the BASELINE-scale 1M-op divergence takes ~1s instead of
    dominating the config's wall time.
    """
    from .storage.change import LazyOps, encode_change_cols_arrays

    d = doc.doc
    base_heads = sorted(d.get_heads())
    base_max = d.max_op
    base_actor = d.actor.bytes
    # counter put op ids in commit order: root puts are ops 1..n by actor 1
    put_id: Dict[str, Tuple[int, bytes]] = {}
    info = d.ops.get_obj((0, 0))
    for prop_idx, run in info.data.props.items():
        name = d.props.get(prop_idx)
        for op in run:
            put_id[name] = (op.id[0], d.actors.get(op.id[1]).bytes)

    # one rng for the whole workload (deterministic, vectorized)
    rng = np.random.default_rng(3000)
    picks = rng.integers(0, len(keys), (n_actors, incs_per_actor))
    counts = np.bincount(picks.reshape(-1), minlength=len(keys))
    expected = {k: int(counts[j]) for j, k in enumerate(keys) if counts[j]}

    # column templates shared by every replica change: incs then 4 puts
    m = incs_per_actor + 4
    key_table = list(keys) + [f"w{j}" for j in range(4)]
    put_ctr = np.asarray([put_id[k][0] for k in keys], np.int64)
    zeros = np.zeros(m, np.int64)
    zeros_u8 = np.zeros(m, np.uint8)
    action = np.concatenate([
        np.full(incs_per_actor, _ACTION_INCREMENT, np.int64),
        np.full(4, _ACTION_PUT, np.int64),
    ])
    pred_num = np.concatenate([
        np.ones(incs_per_actor, np.int64), np.zeros(4, np.int64)
    ])
    # increments carry int 1 (sleb 0x01, meta 0x14); puts carry int i
    inc_meta = np.full(incs_per_actor, (1 << 4) | 4, np.int64)
    inc_raw = b"\x01" * incs_per_actor
    mark_ids = np.full(m, -1, np.int64)

    from .utils.leb128 import sleb_bytes

    # 12 of the 14 columns are identical across replicas (obj, key-elem,
    # insert, action, expand, marks, pred_actor/num, ...) — encode them ONCE
    # via the shared array-native encoder, then per replica only the three
    # varying columns (key ids, pred counters, value payload) are rebuilt.
    template = encode_change_cols_arrays(
        {
            "obj_mask": zeros_u8,
            "obj_ctr": zeros,
            "obj_actor": zeros,
            "key_str_ids": np.concatenate(
                [picks[0], np.arange(len(keys), len(keys) + 4)]
            ),
            "key_str_table": key_table,
            "key_ctr": zeros,
            "key_ctr_mask": zeros_u8,
            "key_actor": zeros,
            "key_actor_mask": zeros_u8,
            "insert": zeros_u8,
            "action": action,
            "val_meta": np.concatenate([inc_meta, np.full(4, (1 << 4) | 4, np.int64)]),
            "val_raw": b"",
            "pred_num": pred_num,
            "pred_ctr": put_ctr[picks[0]],
            "pred_actor": np.ones(incs_per_actor, np.int64),  # base actor
            "expand": zeros_u8,
            "mark_ids": mark_ids,
            "mark_table": [],
        }
    )
    from .storage.change import (
        COL_KEY_STR, COL_PRED_CTR, COL_VAL_META, COL_VAL_RAW,
    )
    base_cols = dict(template)
    # the varying columns are rebuilt per replica below; drop them from the
    # shared template so any accidental reliance fails loudly
    for _c in (COL_KEY_STR, COL_PRED_CTR, COL_VAL_META, COL_VAL_RAW):
        base_cols.pop(_c, None)
    key_tail = np.arange(len(keys), len(keys) + 4)
    ones_p = np.ones(incs_per_actor, np.uint8)
    meta_cache: Dict[int, bytes] = {}
    from . import native as _native

    out = []
    for i in range(n_actors):
        actor = _replica_actor(i)
        put_raw = sleb_bytes(i)
        put_meta = (len(put_raw) << 4) | 4
        vm = meta_cache.get(put_meta)
        if vm is None:
            vm = _native.rle_encode_array(
                np.concatenate([inc_meta, np.full(4, put_meta, np.int64)]),
                np.ones(m, np.uint8), False,
            )
            meta_cache[put_meta] = vm
        cols_d = dict(base_cols)
        cols_d[COL_KEY_STR] = _native.rle_encode_strtab(
            np.concatenate([picks[i], key_tail]), key_table
        )
        cols_d[COL_PRED_CTR] = _native.delta_encode_array(put_ctr[picks[i]], ones_p)
        cols_d[COL_VAL_META] = vm
        cols_d[COL_VAL_RAW] = inc_raw + put_raw * 4
        cols = sorted(cols_d.items())  # chunk columns must ascend by spec
        sc = StoredChange(
            dependencies=list(base_heads),
            actor=actor,
            other_actors=[base_actor],
            seq=1,
            start_op=base_max + 1,
            timestamp=0,
            message=None,
            ops=LazyOps(cols_d, m),
        )
        out.append(build_change(sc, cols=cols))
    return out, expected


def synth_delta_chain(
    base: BaseInfo, trace_edits: Sequence, k: int, ops_per_delta: int,
    offset: int, actor: Optional[bytes] = None,
) -> List[List[StoredChange]]:
    """The incremental workload: K successive small deltas from ONE editing
    replica against a large resident base — each delta is one change whose
    deps chain off the previous delta (seq ascending), exactly what a live
    peer streams over sync. Returns K single-change batches."""
    import copy

    actor = actor if actor is not None else _replica_actor(0)
    out: List[List[StoredChange]] = []
    cur = copy.copy(base)  # shallow view; heads/max_op advance per delta
    lo0 = min(offset // 2, max(len(trace_edits) - ops_per_delta - 1, 0))
    span = max(len(trace_edits) - lo0 - ops_per_delta, 1)
    for i in range(k):
        lo = lo0 + (offset // 2 + i * ops_per_delta) % span
        ch = synth_seq_change(
            cur, actor, trace_edits[lo : lo + ops_per_delta], seed=5000 + i
        )
        if i > 0:  # the committing replica's seq advances along the chain
            ch = build_change(
                StoredChange(
                    dependencies=list(cur.heads),
                    actor=actor,
                    other_actors=ch.other_actors,
                    seq=i + 1,
                    start_op=cur.max_op + 1,
                    timestamp=0,
                    message=None,
                    ops=ch.ops,
                )
            )
        cur = copy.copy(cur)
        cur.heads = [ch.hash]
        cur.max_op = ch.max_op
        out.append([ch])
    return out


# -- the native sequential-apply baseline -----------------------------------


def seq_apply_baseline(
    changes: Sequence[StoredChange], query_obj: Tuple[int, bytes],
    reps: int = 1,
):
    """Run the native sequential apply over ``changes``; returns
    (best-of-``reps`` elapsed seconds, merged text of query_obj).

    The measured equivalent of the reference's sequential Rust
    ``apply_changes`` loop on this host (see BASELINE.md for how this is
    used as the honest baseline). The timed region covers the SAME input
    boundary the framework side is measured from — change chunks with
    retained column bytes — so it includes the columnar change decode
    (reference: change_op_columns.rs iter_ops feeds every applied op) and
    the actor-rank import (automerge.rs:860 import_ops), both via the
    same native codec core the framework uses. It does NOT include the
    reference's B-tree index maintenance or per-op tree seeks beyond a
    hash lookup + Lamport sibling scan, which keeps the model generous
    (faster than the reference), hence the conservative max() with the
    pin. ``reps`` takes the minimum like the framework side's loop.
    """
    import numpy as np

    from . import native
    from .ops.extract import ranked_batch
    from .ops.oplog import ACTOR_BITS

    dt = float("inf")
    flat = None
    for _ in range(max(reps, 1)):
        t0 = time.perf_counter()
        # decode + import: chunk column bytes -> flat causal-order arrays
        actor_bytes = sorted({bytes(a) for ch in changes for a in ch.actors})
        rank_of = {a: i for i, a in enumerate(actor_bytes)}
        r = ranked_batch(list(changes), rank_of)
        a = r["a"]
        n = a["n"]
        prop = r["prop_ids"].astype(np.int32)
        # am_seq_apply's elem convention: 0 = HEAD / map op
        elem = np.where(r["elem"] > 0, r["elem"], 0)
        pred_off = np.bincount(
            r["pred_src"] + 1, minlength=n + 1
        ).cumsum().astype(np.int64)
        # pred edges arrive grouped by source row already (change order)
        rows = native.seq_apply(
            r["id_key"], r["obj"], elem, prop,
            a["action"].astype(np.int32), a["insert"].astype(np.uint8),
            (a["vcode"] == 8).astype(np.uint8),
            pred_off, r["pred_key"],
            (query_obj[0] << ACTOR_BITS) | rank_of[query_obj[1]],
        )
        if time.perf_counter() - t0 < dt:
            dt = time.perf_counter() - t0
            flat = (a, rows)
    a, rows = flat
    from .ops.extract import LazyValues

    vals = LazyValues(a["vcode"], a["voff"], a["vlen"], a["vraw"])
    text = "".join(
        vals[int(r)].value if a["vcode"][r] == 6 else "￼" for r in rows
    )
    return dt, text
