"""Tracing instrumentation (the reference weaves the ``tracing`` crate
through load/commit/insert — automerge.rs:579,600, op_set.rs:232,
transaction/inner.rs:80,122; here the standard logging module plays that
role).

Disabled by default and free when off: every hook is guarded by
``logger.isEnabledFor`` so the hot paths pay one cached attribute check.
Enable with e.g.::

    import logging
    logging.getLogger("automerge_tpu").setLevel(logging.DEBUG)
    logging.basicConfig()

or set AUTOMERGE_TPU_TRACE=1 in the environment before first import.
"""

from __future__ import annotations

import logging
import os
from time import perf_counter as _perf_counter

logger = logging.getLogger("automerge_tpu")

if os.environ.get("AUTOMERGE_TPU_TRACE"):
    logger.setLevel(logging.DEBUG)
    if not logger.handlers:
        logging.basicConfig()

_DEBUG = logging.DEBUG


def enabled() -> bool:
    return logger.isEnabledFor(_DEBUG)


def event(name: str, **fields) -> None:
    """One structured trace line: ``name k=v k=v``."""
    if logger.isEnabledFor(_DEBUG):
        body = " ".join(f"{k}={v}" for k, v in fields.items())
        logger.debug("%s %s", name, body)


# -- counters ---------------------------------------------------------------
# Degradation observability (sync.retry, sync.reset, load.salvaged_chunks,
# ...): recovery paths are rare, so these always accumulate — one dict
# increment — and additionally emit an ``event`` line when tracing is on.

counters: dict = {}


def count(name: str, n: int = 1, **fields) -> None:
    """Increment the named counter and trace it (``name n=… k=v``)."""
    counters[name] = counters.get(name, 0) + n
    if logger.isEnabledFor(_DEBUG):
        event(name, n=n, total=counters[name], **fields)


def reset_counters() -> None:
    counters.clear()


class span:
    """``with span("load", bytes=n):`` — logs entry/exit with wall time."""

    __slots__ = ("name", "fields", "t0")

    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields
        self.t0 = 0.0

    def __enter__(self):
        if logger.isEnabledFor(_DEBUG):
            self.t0 = _perf_counter()
            event(self.name, phase="begin", **self.fields)
        return self

    def __exit__(self, *exc):
        if logger.isEnabledFor(_DEBUG):
            ms = (_perf_counter() - self.t0) * 1e3
            status = "error" if exc[0] else "ok"
            event(self.name, phase="end", status=status, ms=round(ms, 2), **self.fields)
        return False


# -- timed spans -------------------------------------------------------------
# Phase attribution (device.extract, device.h2d, device.kernel,
# device.readback, device.materialize, ...): like the counters these always
# accumulate — two perf_counter reads and a dict update per span — so the
# bench can export wall-time breakdowns without tracing enabled. An
# ``event`` line is additionally emitted when tracing is on.

timings: dict = {}  # name -> [total_seconds, count]


class time:  # noqa: A001 — the public name IS trace.time
    """``with trace.time("device.kernel", rows=n):`` — accumulate wall time
    under the named phase in ``trace.timings``."""

    __slots__ = ("name", "fields", "t0")

    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields
        self.t0 = 0.0

    def __enter__(self):
        self.t0 = _perf_counter()
        return self

    def __exit__(self, *exc):
        dt = _perf_counter() - self.t0
        slot = timings.get(self.name)
        if slot is None:
            timings[self.name] = [dt, 1]
        else:
            slot[0] += dt
            slot[1] += 1
        if logger.isEnabledFor(_DEBUG):
            event(self.name, ms=round(dt * 1e3, 3), **self.fields)
        return False


def reset_timers() -> None:
    timings.clear()


def timing_summary() -> dict:
    """{name: {"s": total seconds, "n": span count}} snapshot."""
    return {k: {"s": round(v[0], 6), "n": v[1]} for k, v in timings.items()}
