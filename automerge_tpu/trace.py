"""Back-compat tracing facade over ``automerge_tpu.obs``.

Historically this module held two bare dicts (``counters``/``timings``)
and standalone ``count``/``time``/``span``/``event`` helpers. The real
implementation now lives in ``obs/`` — a thread-safe labeled metrics
registry, hierarchical spans with Perfetto export, and Prometheus
exposition — and these names are thin shims kept so every existing call
site, test and bench consumer keeps working:

* ``trace.count(name, n, **fields)``  -> ``obs.count`` (lock-protected;
  the old plain-dict increments raced between the RPC server and the
  device staging path).
* ``trace.time(name, **fields)`` / ``trace.span(...)`` -> ``obs.span``:
  both now accumulate into ``trace.timings`` AND feed the span ring
  buffer + per-name latency histograms (p50/p95/p99 via
  ``obs.percentiles``).
* ``trace.counters`` / ``trace.timings`` alias the same dict objects obs
  maintains, so direct reads (bench JSON export, tests) see live data.

Enable per-event log lines with ``AUTOMERGE_TPU_TRACE=1`` (or raise the
``automerge_tpu`` logger to DEBUG); the metric/span accumulation is
always on and cheap.
"""

from __future__ import annotations

from . import obs

logger = obs.logger

enabled = obs.enabled
event = obs.event

# the legacy dict views: same OBJECTS as obs.legacy_* (callers that stash,
# clear and restore their contents — bench.py — keep working)
counters = obs.legacy_counters
timings = obs.legacy_timings


def count(name: str, n: int = 1, **fields) -> None:
    """Increment the named counter and trace it (``name n=… k=v``)."""
    obs.count(name, n, **fields)


# ``with trace.span("load", bytes=n):`` and ``with trace.time("device.kernel",
# rows=n):`` are the same instrument now: a hierarchical obs span. (span
# formerly only logged; it gains the always-on timing accumulation.)
span = obs.span
time = obs.span  # noqa: A001 — the public name IS trace.time

reset_counters = obs.reset_counters
reset_timers = obs.reset_timers
timing_summary = obs.timing_summary
