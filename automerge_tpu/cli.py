"""Command-line interface: export / import / merge / examine / examine-sync
/ change / journal-info / compact / metrics / serve / cluster-router /
cluster-metrics / cluster-history / cluster-top / flight-merge /
perf-report.

Mirrors the reference CLI's subcommands (reference:
rust/automerge-cli/src/main.rs:81-161). Documents read and write the
binary automerge format; export/import speak JSON.

    python -m automerge_tpu export doc.automerge
    python -m automerge_tpu import state.json -o doc.automerge
    python -m automerge_tpu merge a.automerge b.automerge -o merged.automerge
    python -m automerge_tpu examine doc.automerge
    python -m automerge_tpu examine-sync msg.sync
    python -m automerge_tpu change doc.automerge 'set .title "hi"' -o out.automerge
"""

from __future__ import annotations

import argparse
import json
import shlex
import sys
from typing import List, Optional

from .api import AutoDoc
from .expanded import expand_change
from .types import ObjType, ScalarValue


def _read(path: Optional[str]) -> bytes:
    if path is None or path == "-":
        return sys.stdin.buffer.read()
    with open(path, "rb") as f:
        return f.read()


def _write(path: Optional[str], data: bytes) -> None:
    if path is None or path == "-":
        sys.stdout.buffer.write(data)
    else:
        with open(path, "wb") as f:
            f.write(data)


def _load_doc(args) -> AutoDoc:
    doc = AutoDoc.load(
        _read(args.input),
        verify=not args.skip_verifying_heads,
        on_error="salvage" if getattr(args, "salvage", False) else None,
    )
    rep = doc.salvage_report
    if rep is not None and rep.dropped:
        print(f"salvage: {rep.summary()}", file=sys.stderr)
        for d in rep.dropped:
            print(
                f"salvage: dropped span at {d.offset}: {d.reason}"
                + (f" (checksum {d.checksum.hex()})" if d.checksum else ""),
                file=sys.stderr,
            )
    return doc


def _watch_loop(seconds: float, emit) -> int:
    """``--watch`` driver: clear the terminal, render once, sleep,
    repeat. Ctrl-C is the intended exit and returns 0 (a clean status),
    not a traceback."""
    import time

    try:
        while True:
            sys.stdout.write("\x1b[2J\x1b[H")
            sys.stdout.flush()
            rc = emit()
            if rc != 0:
                return rc
            time.sleep(max(0.1, seconds))
    except KeyboardInterrupt:
        sys.stdout.write("\n")
        return 0


def _rpc_once(addr: str, method: str, params, tag: str):
    """One-shot JSON-RPC request over a short-lived TCP connection (the
    perf-report idiom). Returns ``(result, None)`` on success or
    ``(None, exit_code)`` with the error already printed to stderr."""
    import socket

    host, _, port = addr.rpartition(":")
    req = {"id": 1, "method": method}
    if params:
        req["params"] = params
    try:
        with socket.create_connection((host or "127.0.0.1", int(port)),
                                      timeout=10) as sock:
            sock.settimeout(30)
            sock.sendall((json.dumps(req) + "\n").encode())
            raw = sock.makefile("r").readline()
    except (OSError, ValueError) as e:
        print(f"{tag}: {addr}: {e}", file=sys.stderr)
        return None, 1
    if not raw:
        print(f"{tag}: server closed the connection", file=sys.stderr)
        return None, 1
    resp = json.loads(raw)
    if "error" in resp:
        print(f"{tag}: {resp['error']}", file=sys.stderr)
        return None, 1
    return resp["result"], None


def cmd_export(args) -> int:
    doc = _load_doc(args)
    out = json.dumps(doc.hydrate(), indent=2, ensure_ascii=False)
    _write(args.out, (out + "\n").encode())
    return 0


def _import_value(doc, obj, key, value, insert=False):
    from .functional import write_value

    write_value(doc, obj, key, value, insert=insert, str_as_text=True, sort_keys=True)


def cmd_import(args) -> int:
    data = json.loads(_read(args.input).decode())
    if not isinstance(data, dict):
        print("import: top-level JSON value must be an object", file=sys.stderr)
        return 1
    doc = AutoDoc()
    for k in sorted(data):
        _import_value(doc, "_root", k, data[k])
    doc.commit()
    _write(args.out, doc.save())
    return 0


def cmd_merge(args) -> int:
    if not args.input:
        print("merge: provide at least one input file", file=sys.stderr)
        return 1
    doc = AutoDoc.load(_read(args.input[0]))
    for path in args.input[1:]:
        doc.merge(AutoDoc.load(_read(path)))
    _write(args.out, doc.save())
    return 0


def cmd_examine(args) -> int:
    doc = _load_doc(args)
    changes = [expand_change(a.stored) for a in doc.doc.history]
    _write(args.out, (json.dumps(changes, indent=2) + "\n").encode())
    return 0


def cmd_examine_sync(args) -> int:
    from .sync import Message
    from .sync.session import SESSION_FRAME_TYPE, decode_frame

    data = _read(args.input)
    frame = None
    if data[:1] == bytes([SESSION_FRAME_TYPE]):
        epoch, flags, seq, inner = decode_frame(data)
        frame = {"epoch": epoch, "flags": flags, "seq": seq}
        data = inner
    if frame is not None and not data:
        _write(args.out, (json.dumps({"frame": frame}, indent=2) + "\n").encode())
        return 0
    msg = Message.decode(data)
    out = {
        "heads": [h.hex() for h in msg.heads],
        "need": [h.hex() for h in msg.need],
        "have": [
            {
                "lastSync": [h.hex() for h in h_.last_sync],
                "bloom": h_.bloom.to_bytes().hex(),
            }
            for h_ in msg.have
        ],
        "changes": [expand_change(c) for c in msg.changes],
    }
    if frame is not None:
        out = {"frame": frame, "message": out}
    _write(args.out, (json.dumps(out, indent=2) + "\n").encode())
    return 0


def _resolve_path(doc, path: str):
    """'.a.b[2].c' -> (object id, final key). Root path '.' is ('_root', None)."""
    obj = "_root"
    parts: List = []
    for seg in path.strip().lstrip(".").split("."):
        if not seg:
            continue
        while "[" in seg:
            name, rest = seg.split("[", 1)
            idx, seg = rest.split("]", 1)
            if name:
                parts.append(name)
            parts.append(int(idx))
        if seg:
            parts.append(seg)
    if not parts:
        return obj, None
    for p in parts[:-1]:
        val = doc.get(obj, p)
        if val is None or val[0][0] != "obj":
            raise ValueError(f"path segment {p!r} is not an object")
        obj = val[0][2]
    return obj, parts[-1]


def _script_value(tok: str):
    try:
        return json.loads(tok)
    except json.JSONDecodeError:
        return tok


def cmd_change(args) -> int:
    """Apply an edit script: set/insert/delete/increment/splice commands
    (reference: automerge-cli/src/change.rs script language)."""
    doc = AutoDoc.load(_read(args.input)) if args.input else AutoDoc()
    script = args.script
    if script == "-":
        script_lines = sys.stdin.read().splitlines()
    else:
        script_lines = script.split(";")
    for line in script_lines:
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        toks = shlex.split(line)
        cmd, path = toks[0].lower(), toks[1]
        obj, key = _resolve_path(doc, path)
        if cmd == "set":
            value = _script_value(toks[2])
            if isinstance(value, (dict, list, str)) and not isinstance(value, bool):
                _import_value(doc, obj, key, value)
            else:
                doc.put(obj, key, value)
        elif cmd == "insert":
            value = _script_value(toks[2])
            if isinstance(value, (dict, list, str)) and not isinstance(value, bool):
                _import_value(doc, obj, key, value, insert=True)
            else:
                doc.insert(obj, key, value)
        elif cmd in ("delete", "del"):
            doc.delete(obj, key)
        elif cmd in ("increment", "inc"):
            doc.increment(obj, key, int(toks[2]) if len(toks) > 2 else 1)
        elif cmd == "splice":
            val = doc.get(obj, key)
            if val is None or val[0][0] != "obj":
                raise ValueError(f"splice target {path!r} is not a text object")
            doc.splice_text(val[0][2], int(toks[2]), int(toks[3]), toks[4] if len(toks) > 4 else "")
        elif cmd == "counter":
            doc.put(obj, key, ScalarValue("counter", int(toks[2])))
        else:
            print(f"change: unknown command {cmd!r}", file=sys.stderr)
            return 1
    doc.commit()
    _write(args.out, doc.save())
    return 0


def cmd_journal_info(args) -> int:
    """Report a durable document directory's journal state — read-only
    (a torn tail is reported but NOT truncated; ``open``/``compact`` do
    the repairing)."""
    import os

    from .storage.durable import JOURNAL_NAME, SNAPSHOT_NAME
    from .storage.journal import (
        REC_CHANGE,
        REC_META,
        salvage_header_scan,
        scan_records,
    )

    jpath = os.path.join(args.input, JOURNAL_NAME)
    spath = os.path.join(args.input, SNAPSHOT_NAME)
    if not os.path.exists(jpath):
        print(f"journal-info: no journal at {jpath}", file=sys.stderr)
        return 1
    with open(jpath, "rb") as f:
        data = f.read()
    records, tail = scan_records(data)
    if tail.reason == "bad journal magic":
        # report what open()'s header salvage will actually recover (the
        # SAME helper it uses), not a misleading total loss
        records = salvage_header_scan(data)
        kept = sum(r.end - r.offset for r in records)
        tail = tail._replace(
            # the file as stored is unusable until open() rewrites it; the
            # records count + reason carry the actual recovery story
            valid_bytes=0,
            records=len(records),
            reason=(
                "bad journal magic (header will be rewritten on open; "
                f"{len(records)} records / {kept} bytes recoverable)"
            ),
        )
    info = {
        "records": len(records),
        "change_records": sum(1 for r in records if r.rec_type == REC_CHANGE),
        "meta_records": sum(1 for r in records if r.rec_type == REC_META),
        "bytes": tail.total_bytes,
        "valid_bytes": tail.valid_bytes,
        # any nonempty reason is reported, even when every record remains
        # recoverable (e.g. a damaged header open() will rewrite)
        "torn_tail": (
            {"reason": tail.reason, "dropped_bytes": tail.dropped_bytes}
            if (tail.torn or tail.reason)
            else None
        ),
        "snapshot_bytes": (
            os.path.getsize(spath) if os.path.exists(spath) else None
        ),
    }
    if os.path.exists(spath):
        from .storage.runsnap import MAGIC as _ARSN_MAGIC

        with open(spath, "rb") as f:
            head = f.read(len(_ARSN_MAGIC))
        info["snapshot_codec"] = (
            "runsnap" if head == _ARSN_MAGIC else "chunk"
        )
    rc = 0
    if getattr(args, "verify", False):
        # deep read-back scan (the scrubber's own core): every journal
        # record CRC-checked and every snapshot chunk walked strictly —
        # the first bad byte offset names where the rot starts
        from .integrity import verify_doc_dir

        reports = verify_doc_dir(args.input)
        info["verify"] = [
            {
                "kind": r.kind,
                "ok": r.ok,
                "bytes": r.total_bytes,
                "valid_bytes": r.valid_bytes,
                "units": r.units,
                "first_bad_offset": r.first_bad_offset,
                **({"reason": r.reason} if r.reason else {}),
            }
            for r in reports
        ]
        bad = [r for r in reports if not r.ok]
        if bad:
            rc = 1
            for r in bad:
                print(
                    f"journal-info: {r.kind} corrupt at byte "
                    f"{r.first_bad_offset} ({r.reason or 'checksum'})",
                    file=sys.stderr,
                )
    _write(args.out, (json.dumps(info, indent=2) + "\n").encode())
    return rc


def cmd_compact(args) -> int:
    """Force a snapshot + journal truncation on a durable document
    directory (recovering any torn tail on the way in)."""
    import os

    from .api import AutoDoc
    from .storage.durable import JOURNAL_NAME

    # opening a mistyped path would CREATE a fresh durable doc there;
    # compacting only ever makes sense on one that already exists
    if not os.path.exists(os.path.join(args.input, JOURNAL_NAME)):
        print(f"compact: no durable document at {args.input}", file=sys.stderr)
        return 1
    from .storage.journal import JournalError

    try:
        dd = AutoDoc.open(args.input, fsync="never")
    except JournalError as e:
        print(f"compact: {e}", file=sys.stderr)
        return 1
    try:
        before = dd.journal.record_count
        if not dd.compact():
            print("compact: skipped (journal busy)", file=sys.stderr)
            return 1
        out = {
            "compacted": True,
            "records_before": before,
            "records_after": dd.journal.record_count,
            "journal_bytes": dd.journal.size_bytes,
        }
    finally:
        dd.close()
    _write(args.out, (json.dumps(out, indent=2) + "\n").encode())
    return 0


def cmd_metrics(args) -> int:
    """Exercise the instrumented load path on a document (a save file or
    a durable directory), then dump the metrics registry — Prometheus
    text by default, ``--format json`` for the structured snapshot,
    ``--trace-out trace.json`` for a Perfetto/Chrome-trace span dump of
    everything the load did."""
    import os

    from . import obs

    if args.input:
        if os.path.isdir(args.input):
            from .storage.journal import JournalError

            try:
                dd = AutoDoc.open(args.input, fsync="never")
            except JournalError as e:
                print(f"metrics: {e}", file=sys.stderr)
                return 1
            try:
                n = len(dd.doc.history)
            finally:
                dd.close()
            print(f"metrics: replayed durable doc ({n} changes)",
                  file=sys.stderr)
        else:
            doc = AutoDoc.load(_read(args.input), on_error="salvage")
            rep = doc.salvage_report
            if rep is not None and rep.dropped:
                print(f"metrics: {rep.summary()}", file=sys.stderr)
    def emit() -> int:
        if args.format == "json":
            body = json.dumps(
                {
                    "metrics": obs.snapshot(),
                    "counters": dict(obs.legacy_counters),
                    "timings": obs.timing_summary(),
                },
                indent=2,
            ) + "\n"
        else:
            body = obs.render_prometheus()
        _write(args.out, body.encode())
        return 0

    if args.watch:
        return _watch_loop(args.watch, emit)
    emit()
    if args.trace_out:
        n_spans = obs.export_trace(args.trace_out)
        print(
            f"metrics: wrote {n_spans} spans to {args.trace_out} "
            "(open at https://ui.perfetto.dev)",
            file=sys.stderr,
        )
    return 0


def cmd_cluster_metrics(args) -> int:
    """Scrape a cluster router's ``clusterMetrics`` method: every node's
    Prometheus exposition merged into one family set, each sample
    labeled ``node="<addr>"`` (the router itself is ``node="router"``).
    Unreachable nodes are reported on stderr, never fatal."""

    def emit() -> int:
        result, rc = _rpc_once(args.router, "clusterMetrics", None,
                               "cluster-metrics")
        if result is None:
            return rc
        for bad in result.get("unreachable", ()):
            print(f"cluster-metrics: unreachable {bad['node']}: "
                  f"{bad['error']}", file=sys.stderr)
        if args.format == "json":
            _write(args.out, (json.dumps(result, indent=2) + "\n").encode())
        else:
            _write(args.out, result["body"].encode())
        return 0

    if args.watch:
        return _watch_loop(args.watch, emit)
    return emit()


def cmd_cluster_history(args) -> int:
    """Query a node's in-memory history rings (obs/history.py): the
    1s/10s/60s downsampled recent past of the allowlisted metrics,
    fetched over the ``historyStatus`` RPC. Works against any server or
    cluster node address (followers answer too)."""
    params = {}
    if args.metric:
        params["name"] = args.metric
    if args.tier is not None:
        params["tier"] = args.tier

    def emit() -> int:
        result, rc = _rpc_once(args.connect, "historyStatus", params,
                               "cluster-history")
        if result is None:
            return rc
        if args.format == "json":
            _write(args.out, (json.dumps(result, indent=2) + "\n").encode())
            return 0
        lines = [
            f"history: tiers {result.get('tiers')}  "
            f"samples {result.get('samples', 0)}  "
            f"series cap {result.get('cap')}  "
            f"dropped {result.get('droppedSeries', 0)}"
        ]
        for s in result.get("series") or ():
            lines.append(f"{s.get('name')} ({s.get('type')})")
            tiers = s.get("tiers") or {}
            for t in sorted(tiers, key=int):
                slots = tiers[t][-args.last:]
                if not slots:
                    continue
                if s.get("type") == "counter":
                    body = "  ".join(
                        f"{sl.get('delta', 0.0):g}" for sl in slots)
                else:
                    body = "  ".join(
                        f"{sl.get('max', 0.0):g}" for sl in slots)
                lines.append(f"  tier {t}: {body}")
        _write(args.out, ("\n".join(lines) + "\n").encode())
        return 0

    if args.watch:
        return _watch_loop(args.watch, emit)
    return emit()


def cmd_cluster_top(args) -> int:
    """Live cluster heat view: the router's ``clusterAdvise`` RPC —
    per-group load from the doc-heat tables, follower staleness, and
    the placement advisor's ranked, explained, report-only
    recommendations. ``--watch N`` turns it into a top(1)-style
    redraw loop."""
    from .cluster import advisor

    params = {}
    if args.snapshot:
        params["snapshot"] = True

    def emit() -> int:
        result, rc = _rpc_once(args.router, "clusterAdvise", params,
                               "cluster-top")
        if result is None:
            return rc
        if args.format == "json":
            _write(args.out, (json.dumps(result, indent=2) + "\n").encode())
        else:
            _write(args.out,
                   advisor.render_text(result, top=args.top).encode())
        return 0

    if args.watch:
        return _watch_loop(args.watch, emit)
    return emit()


def cmd_flight_merge(args) -> int:
    """Stitch flight-recorder dumps (``flight-*.json``, written by
    server processes on exit/failover) into one Perfetto/Chrome-trace
    timeline: one pid per process, clocks aligned from RTT-midpoint
    samples where available (wall clock otherwise), span parent/link ids
    connecting one request's spans across every process it touched."""
    import glob
    import os

    from .obs.flight import merge_flights

    paths = []
    for inp in args.input:
        if os.path.isdir(inp):
            paths.extend(sorted(glob.glob(os.path.join(inp, "flight-*.json"))))
        else:
            paths.append(inp)
    if not paths:
        print("flight-merge: no flight dumps found", file=sys.stderr)
        return 1
    try:
        doc, info = merge_flights(paths)
    except (OSError, ValueError, KeyError) as e:
        print(f"flight-merge: {e}", file=sys.stderr)
        return 1
    _write(args.out, json.dumps(doc).encode())
    print(
        f"flight-merge: {info['spans']} spans from "
        f"{len(info['processes'])} processes "
        "(open at https://ui.perfetto.dev)",
        file=sys.stderr,
    )
    for name, p in sorted(info["processes"].items()):
        print(f"flight-merge:   pid {p['pid']}: {name} "
              f"({p['spans']} spans, clock: {p['aligned']})",
              file=sys.stderr)
    return 0


def cmd_perf_report(args) -> int:
    """Render the drain-cycle performance observatory (obs/prof.py):
    live from a running server's ``perfStatus`` RPC (``--connect``), or
    offline from flight-recorder dumps — every finished drain cycle
    lands in the flight ring as a ``drain.cycle_report`` event, so a
    dead process's last dump still answers "where did the drain wall
    clock go". The text report includes the h2d byte line (actual
    staged bytes vs dense equivalent and the compress ratio — the
    compressed-residency win per drain)."""
    import glob
    import os
    import socket

    from .obs import prof

    if args.connect:
        host, _, port = args.connect.rpartition(":")
        req = {"id": 1, "method": "perfStatus",
               "params": {"top": args.top}}
        try:
            with socket.create_connection((host or "127.0.0.1", int(port)),
                                          timeout=10) as sock:
                sock.settimeout(30)
                sock.sendall((json.dumps(req) + "\n").encode())
                raw = sock.makefile("r").readline()
        except (OSError, ValueError) as e:
            print(f"perf-report: {args.connect}: {e}", file=sys.stderr)
            return 1
        if not raw:
            print("perf-report: server closed the connection",
                  file=sys.stderr)
            return 1
        resp = json.loads(raw)
        if "error" in resp:
            print(f"perf-report: {resp['error']}", file=sys.stderr)
            return 1
        summary = resp["result"]
    else:
        paths = []
        for inp in args.input:
            if os.path.isdir(inp):
                paths.extend(
                    sorted(glob.glob(os.path.join(inp, "flight-*.json"))))
            else:
                paths.append(inp)
        if not paths:
            print("perf-report: provide --connect HOST:PORT or flight "
                  "dumps / directories", file=sys.stderr)
            return 1
        events = []
        for p in paths:
            with open(p) as f:
                d = json.load(f)
            if d.get("format") != "automerge_tpu-flight-v1":
                print(f"perf-report: {p}: not a flight dump",
                      file=sys.stderr)
                return 1
            events.extend(d.get("events", ()))
        summary = prof.summarize_flight_events(events)
        if not summary["cycles"]:
            print("perf-report: no drain.cycle_report events in the "
                  "given dumps (profiling off, or no drains ran)",
                  file=sys.stderr)
            return 1
    if args.format == "json":
        _write(args.out, (json.dumps(summary, indent=2) + "\n").encode())
    else:
        _write(args.out, prof.render_text(summary, top=args.top).encode())
    return 0


def cmd_serve(args) -> int:
    """Run the concurrent JSON-RPC server (serve/server.py) over TCP or
    a unix-domain socket — the same method surface as the stdio frontend
    (``python -m automerge_tpu.rpc``) with per-document parallelism,
    group-commit durability and backpressure. Delegates to rpc.main so
    both entry points stay behaviourally identical."""
    from .rpc import main as rpc_main

    argv = []
    if args.socket:
        argv += ["--socket", args.socket]
    if args.unix:
        argv += ["--unix", args.unix]
    if args.durable:
        argv += ["--durable", args.durable]
    if args.workers is not None:
        argv += ["--workers", str(args.workers)]
    if not args.socket and not args.unix:
        print("serve: provide --socket HOST:PORT or --unix PATH "
              "(plain stdio mode is `python -m automerge_tpu.rpc`)",
              file=sys.stderr)
        return 1
    return rpc_main(argv)


def cmd_cluster_router(args) -> int:
    """Run the cluster router tier (cluster/router.py): consistent-hash
    document placement over backend shard groups, heartbeat-driven
    leader failover with promotion from the longest durable acked
    prefix, and live shard migration. Delegates to the router's own
    main so the module entry point stays behaviourally identical."""
    from .cluster.router import main as router_main

    argv = ["--listen", args.listen]
    for g in args.group:
        argv += ["--group", g]
    if args.heartbeat is not None:
        argv += ["--heartbeat", str(args.heartbeat)]
    if args.miss_limit is not None:
        argv += ["--miss-limit", str(args.miss_limit)]
    return router_main(argv)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="automerge_tpu", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    def add(name, fn, **kw):
        sp = sub.add_parser(name, **kw)
        sp.set_defaults(fn=fn)
        sp.add_argument("-o", "--out", default=None, help="output file (default stdout)")
        return sp

    sp = add("export", cmd_export, help="document -> JSON")
    sp.add_argument("input", nargs="?", help="input .automerge file (default stdin)")
    sp.add_argument("--skip-verifying-heads", action="store_true")
    sp.add_argument("--salvage", action="store_true",
                    help="recover what a damaged save still holds "
                         "(dropped spans are reported on stderr)")

    sp = add("import", cmd_import, help="JSON -> document")
    sp.add_argument("input", nargs="?", help="input JSON file (default stdin)")

    sp = add("merge", cmd_merge, help="merge N documents into one")
    sp.add_argument("input", nargs="*", help="input .automerge files")

    sp = add("examine", cmd_examine, help="dump a document's changes as JSON")
    sp.add_argument("input", nargs="?", help="input .automerge file (default stdin)")
    sp.add_argument("--skip-verifying-heads", action="store_true")
    sp.add_argument("--salvage", action="store_true",
                    help="recover what a damaged save still holds "
                         "(dropped spans are reported on stderr)")

    sp = add("examine-sync", cmd_examine_sync, help="decode a sync message")
    sp.add_argument("input", nargs="?", help="input sync message file (default stdin)")

    sp = add("journal-info", cmd_journal_info,
             help="inspect a durable document directory's journal (read-only)")
    sp.add_argument("input", help="durable document directory")
    sp.add_argument("--verify", action="store_true",
                    help="deep read-back scan: CRC-check every journal "
                         "record and walk every snapshot chunk; exits 1 "
                         "and reports the first bad offset on corruption")

    sp = add("compact", cmd_compact,
             help="snapshot a durable document and truncate its journal")
    sp.add_argument("input", help="durable document directory")

    sp = add("metrics", cmd_metrics,
             help="load a document (file or durable dir) and dump the "
                  "metrics registry (Prometheus text or JSON)")
    sp.add_argument("input", nargs="?",
                    help="optional .automerge file or durable document "
                         "directory to load first (instruments the load)")
    sp.add_argument("--format", choices=("prometheus", "json"),
                    default="prometheus")
    sp.add_argument("--trace-out", default=None, metavar="PATH",
                    help="also export recorded spans as Perfetto/"
                         "Chrome-trace JSON to PATH")
    sp.add_argument("--watch", type=float, default=None, metavar="SECONDS",
                    help="redraw the dump every SECONDS until Ctrl-C")

    sp = sub.add_parser(
        "serve",
        help="run the concurrent JSON-RPC server over TCP or unix socket",
    )
    sp.set_defaults(fn=cmd_serve)
    sp.add_argument("--socket", metavar="HOST:PORT", default=None,
                    help="TCP listen address (port 0 picks a free port)")
    sp.add_argument("--unix", metavar="PATH", default=None,
                    help="unix-domain socket path")
    sp.add_argument("--durable", metavar="DIR", default=None,
                    help="enable openDurable persistence under DIR")
    sp.add_argument("--workers", type=int, default=None,
                    help="worker pool size (default "
                         "AUTOMERGE_TPU_SERVE_WORKERS or 8)")

    sp = add("cluster-metrics", cmd_cluster_metrics,
             help="scrape a cluster router: every node's metrics merged "
                  "into one family set with node labels")
    sp.add_argument("router", metavar="HOST:PORT",
                    help="router address to scrape")
    sp.add_argument("--format", choices=("prometheus", "json"),
                    default="prometheus")
    sp.add_argument("--watch", type=float, default=None, metavar="SECONDS",
                    help="re-scrape and redraw every SECONDS until Ctrl-C")

    sp = add("cluster-history", cmd_cluster_history,
             help="query a node's history rings: downsampled recent "
                  "past of the allowlisted metrics")
    sp.add_argument("connect", metavar="HOST:PORT",
                    help="server or cluster node address")
    sp.add_argument("--metric", default=None,
                    help="restrict to one metric family name")
    sp.add_argument("--tier", type=int, default=None,
                    help="restrict to one tier index (0=1s, 1=10s, 2=60s)")
    sp.add_argument("--last", type=int, default=20,
                    help="slots shown per tier in text mode")
    sp.add_argument("--format", choices=("text", "json"), default="text")
    sp.add_argument("--watch", type=float, default=None, metavar="SECONDS",
                    help="redraw every SECONDS until Ctrl-C")

    sp = add("cluster-top", cmd_cluster_top,
             help="live cluster heat view: group loads, staleness, and "
                  "the placement advisor's report-only recommendations")
    sp.add_argument("router", metavar="HOST:PORT",
                    help="cluster router address")
    sp.add_argument("--top", type=int, default=None,
                    help="recommendations shown in text mode")
    sp.add_argument("--snapshot", action="store_true",
                    help="include the raw telemetry snapshot (json mode)")
    sp.add_argument("--format", choices=("text", "json"), default="text")
    sp.add_argument("--watch", type=float, default=None, metavar="SECONDS",
                    help="redraw every SECONDS until Ctrl-C")

    sp = add("perf-report", cmd_perf_report,
             help="drain-cycle stage attribution: host/device split, "
                  "occupancy, top docs — live (--connect) or from "
                  "flight dumps")
    sp.add_argument("input", nargs="*",
                    help="flight-*.json dumps (or directories holding "
                         "them) for offline mode")
    sp.add_argument("--connect", metavar="HOST:PORT", default=None,
                    help="scrape a live server's perfStatus RPC instead")
    sp.add_argument("--format", choices=("text", "json"), default="text")
    sp.add_argument("--top", type=int, default=8,
                    help="rows in the expensive-docs table")

    sp = add("flight-merge", cmd_flight_merge,
             help="merge flight-recorder dumps from several processes "
                  "into one clock-aligned Perfetto timeline")
    sp.add_argument("input", nargs="+",
                    help="flight-*.json dump files (or directories "
                         "holding them)")

    sp = sub.add_parser(
        "cluster-router",
        help="run the cluster router: consistent-hash placement, "
             "leader failover, live shard migration",
    )
    sp.set_defaults(fn=cmd_cluster_router)
    sp.add_argument("--listen", metavar="HOST:PORT", default="127.0.0.1:0",
                    help="client-facing listen address")
    sp.add_argument("--group", action="append", required=True,
                    metavar="ADDR,ADDR,...",
                    help="one shard group: comma-separated node "
                         "addresses, leader first (repeatable)")
    sp.add_argument("--heartbeat", type=float, default=None,
                    help="leader liveness poll interval, seconds")
    sp.add_argument("--miss-limit", type=int, default=None,
                    help="consecutive missed heartbeats before failover")

    sp = add("change", cmd_change, help="apply an edit script to a document")
    sp.add_argument("input", nargs="?", help="input .automerge file (omit to start empty)")
    sp.add_argument(
        "script",
        help="';'-separated commands: set PATH VALUE | insert PATH VALUE | "
        "delete PATH | increment PATH [N] | splice PATH POS DEL TEXT | "
        "counter PATH N  ('-' reads commands from stdin, one per line)",
    )
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
