#!/usr/bin/env python
"""Benchmark: edit-trace N-way fan-in merge, device kernel vs host apply.

The north-star workload (BASELINE.json): K divergent replicas of a text
document built from the canonical edit trace (reference:
rust/edit-trace/edits.json, 259,778 real editing operations) merged into
one converged document. The device path extracts columns with the native
codec core and resolves the whole merged op log in one batched kernel
(automerge_tpu/ops); the baseline is the host-side sequential apply loop
(automerge_tpu/core), the same algorithm shape as the reference's
``apply_changes``.

K replicas are produced by replaying distinct trace slices on a few real
forks, then amplifying each divergent change under fresh actor ids —
structurally identical concurrent edits from many actors, the same shape
the reference's fork/merge benchmark configs describe.

Prints ONE JSON line:
  {"metric": ..., "value": ops/sec through the device merge path
   (extraction + kernel), "unit": "ops/s",
   "vs_baseline": speedup over host sequential merge}
"""

import json
import os
import sys
import time

import numpy as np

TRACE = "/root/reference/rust/edit-trace/edits.json"

BASE_EDITS = int(os.environ.get("BENCH_BASE_EDITS", "20000"))
REAL_FORKS = int(os.environ.get("BENCH_REAL_FORKS", "8"))
AMPLIFY = int(os.environ.get("BENCH_AMPLIFY", "16"))  # replicas = 8*16 = 128
FORK_EDITS = int(os.environ.get("BENCH_FORK_EDITS", "400"))
REPS = int(os.environ.get("BENCH_REPS", "3"))


def load_trace():
    if os.path.exists(TRACE):
        with open(TRACE) as f:
            return json.load(f)
    # synthetic fallback: same shape as the trace, deterministic
    rng = np.random.default_rng(0)
    edits, length = [], 0
    for _ in range(BASE_EDITS + REAL_FORKS * FORK_EDITS + 1000):
        if length == 0 or rng.random() < 0.85:
            pos = int(rng.integers(0, length + 1))
            edits.append([pos, 0, "x"])
            length += 1
        else:
            pos = int(rng.integers(0, length))
            edits.append([pos, 1])
            length -= 1
    return edits


def apply_edits(doc, text_obj, edits):
    for e in edits:
        ln = doc.length(text_obj)
        pos = min(e[0], ln)
        ndel = min(e[1], ln - pos)
        doc.splice_text(text_obj, pos, ndel, "".join(e[2:]))


def amplify_change(stored, new_actor: bytes):
    """Re-author a divergent change under a fresh actor id.

    The ops are position-identical concurrent edits by another actor —
    exactly what K users typing the same places produces. Chunk-local op
    encodings reference the author as actor 0, so only the actor table
    changes; build_change recomputes bytes and hash.
    """
    from automerge_tpu.storage.change import StoredChange, build_change

    return build_change(
        StoredChange(
            dependencies=list(stored.dependencies),
            actor=new_actor,
            other_actors=list(stored.other_actors),
            seq=stored.seq,
            start_op=stored.start_op,
            timestamp=stored.timestamp,
            message=stored.message,
            ops=list(stored.ops),
        )
    )


def main():
    from automerge_tpu.api import AutoDoc
    from automerge_tpu.core.document import Document
    from automerge_tpu.ops import DeviceDoc, OpLog
    from automerge_tpu.ops.merge import merge_columns, merge_kernel
    from automerge_tpu.types import ActorId, ObjType

    trace = load_trace()
    t0 = time.perf_counter()
    base = AutoDoc(actor=ActorId(bytes([1]) * 16))
    text = base.put_object("_root", "text", ObjType.TEXT)
    apply_edits(base, text, trace[:BASE_EDITS])
    base.commit()
    t_base = time.perf_counter() - t0

    # real forks: distinct trace slices replayed on top of the base
    t0 = time.perf_counter()
    divergent = []
    for i in range(REAL_FORKS):
        f = base.fork(actor=ActorId(bytes([2]) * 15 + bytes([i])))
        lo = BASE_EDITS + i * FORK_EDITS
        apply_edits(f, text, trace[lo : lo + FORK_EDITS])
        f.commit()
        divergent.append(f.doc.history[-1].stored)
    # amplification: the same divergence re-authored by more actors
    changes = [a.stored for a in base.doc.history]
    for k in range(AMPLIFY):
        for i, d in enumerate(divergent):
            if k == 0:
                changes.append(d)
            else:
                changes.append(
                    amplify_change(d, bytes([3]) * 14 + bytes([k, i]))
                )
    t_forks = time.perf_counter() - t0
    n_replicas = REAL_FORKS * AMPLIFY

    # --- device path: columnar extraction + batched merge kernel -----------
    import jax
    import jax.numpy as jnp

    t0 = time.perf_counter()
    log = OpLog.from_changes(changes)
    t_extract = time.perf_counter() - t0
    padded = log.padded_columns()
    # device-resident timing: columns stay on chip, outputs are blocked on
    # but not transferred (transfer costs are environment-specific; readback
    # uses the hybrid native-walk path via merge_columns below)
    cols = {k: jnp.asarray(v) for k, v in padded.items()}
    jax.block_until_ready(cols)
    jax.block_until_ready(merge_kernel(cols))  # warmup / compile
    t_kernel = min(
        _timed(lambda: jax.block_until_ready(merge_kernel(cols)))
        for _ in range(REPS)
    )
    t_device = t_extract + t_kernel
    res = merge_columns(padded)

    # --- host baseline: sequential apply of the same changes ---------------
    t0 = time.perf_counter()
    host = Document(ActorId(bytes([9]) * 16))
    host.apply_changes(changes)
    t_host = time.perf_counter() - t0

    # sanity: converged state must match
    dev = DeviceDoc(log, res)
    assert dev.text(text) == host.text(text), "device/host merge divergence"

    ops = log.n
    dev_rate = ops / t_device
    host_rate = ops / t_host
    result = {
        "metric": "edit_trace_fanin_merge_ops_per_sec",
        "value": round(dev_rate, 1),
        "unit": "ops/s",
        "vs_baseline": round(dev_rate / host_rate, 2),
    }
    print(json.dumps(result))
    if os.environ.get("BENCH_VERBOSE"):
        print(
            json.dumps(
                {
                    "ops_merged": ops,
                    "replicas": n_replicas,
                    "capacity": int(len(padded["action"])),
                    "t_extract_s": round(t_extract, 4),
                    "t_kernel_s": round(t_kernel, 4),
                    "t_host_merge_s": round(t_host, 3),
                    "t_base_build_s": round(t_base, 3),
                    "t_fork_build_s": round(t_forks, 3),
                    "host_ops_per_sec": round(host_rate, 1),
                    "kernel_only_ops_per_sec": round(ops / t_kernel, 1),
                    "device": str(jax.devices()[0]),
                },
            ),
            file=sys.stderr,
        )


def _timed(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


if __name__ == "__main__":
    main()
