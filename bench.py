#!/usr/bin/env python
"""Benchmark driver: the BASELINE.md configs on real hardware.

Primary metric (BASELINE.json): ops/sec merged on the edit-trace N-replica
fan-in through the full device path (columnar extraction + batched merge
kernel + readback), vs the sequential-apply baseline. The baseline divisor
is the FASTER of (a) the measured native C++ sequential apply on this host
(automerge_tpu/bench.py seq_apply_baseline — the reference's
apply_changes loop shape, automerge.rs:1258-1280, natively compiled) and
(b) the pinned Rust estimate documented in BASELINE.md — i.e. the
conservative choice.

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": "ops/s", "vs_baseline": ...,
   "configs": {replay, fanin, mapcounter, rga, sync}}

Env knobs: BENCH_BASE_EDITS, BENCH_REPLICAS, BENCH_FORK_EDITS,
BENCH_REPLAY_EDITS, BENCH_MC_ACTORS, BENCH_MC_INCS, BENCH_RGA_ACTORS,
BENCH_RGA_OPS, BENCH_SYNC_OPS, BENCH_HOST_CAP, BENCH_VERBOSE.
"""

import json
import os
import sys
import time

# Pinned Rust-reference throughput estimates (ops/s) — see BASELINE.md
# "Pinned baseline" for the reasoning. No Rust toolchain exists in this
# image; the measured native C++ sequential apply below is the primary
# baseline and these pins act as a floor so vs_baseline can never benefit
# from a slow native build.
RUST_PIN_REPLAY = 500_000.0   # local transaction replay (edit-trace bench)
RUST_PIN_APPLY = 250_000.0    # remote apply_changes (per-op seek/insert)


# every knob resolved through env_int / env_flag lands here, so the
# output JSON carries the exact configuration that produced it — the
# BENCH_r0*.json trajectory stays self-describing across PRs
RESOLVED_CONFIG = {}

BENCH_SCHEMA_VERSION = 2


def env_int(name, default):
    v = int(os.environ.get(name, default))
    RESOLVED_CONFIG[name] = v
    return v


def env_flag(name, default=""):
    v = os.environ.get(name, default)
    RESOLVED_CONFIG[name] = v
    return v


def git_commit():
    """The repo HEAD this bench ran against (None outside a checkout)."""
    import subprocess

    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10,
        )
        return out.stdout.strip() or None if out.returncode == 0 else None
    except Exception:
        return None


def host_fingerprint():
    """Which box produced these numbers. scripts/ci/perf_gate refuses to
    compare trajectory points whose fingerprints differ — a number from
    a different host is a different experiment, not a regression."""
    import platform

    fp = {
        "cpu_count": os.cpu_count(),
        "machine": platform.machine(),
        "platform": platform.platform(),
        "python": platform.python_version(),
    }
    try:
        import jax

        fp["jax_backend"] = jax.default_backend()
        fp["jax_device_count"] = jax.device_count()
    except Exception:
        fp["jax_backend"] = None
        fp["jax_device_count"] = 0
    return fp


def main():
    # Benchmark hygiene (what pytest-benchmark and criterion do): cyclic-GC
    # pauses are runtime noise, not framework cost — the store's bulk builds
    # allocate ~1M objects and a generational collection walking them lands
    # at an arbitrary later point, skewing whichever phase it lands in.
    import gc

    gc.disable()
    import resource as _resource

    import numpy as np

    from automerge_tpu import bench as W
    from automerge_tpu.api import AutoDoc
    from automerge_tpu.core.document import Document
    from automerge_tpu.ops import DeviceDoc, OpLog
    from automerge_tpu.ops.merge import merge_columns
    from automerge_tpu.sync import SyncState
    from automerge_tpu.types import ActorId

    verbose = env_flag("BENCH_VERBOSE")
    reps = env_int("BENCH_REPS", 3)  # best-of-N, one knob for every config
    results = {}

    # per-config wall clock: elapsed seconds between consecutive marks,
    # summed to a total at the end — the additive number perf_gate tracks
    # so a config that quietly doubles its setup cost is caught even when
    # its headline throughput metric holds steady
    wall_s = {}
    _wall_prev = [time.perf_counter()]

    def wall_mark(config):
        now = time.perf_counter()
        wall_s[config] = round(now - _wall_prev[0], 3)
        _wall_prev[0] = now

    def note(msg):
        if verbose:
            print(msg, file=sys.stderr, flush=True)

    trace = W.load_trace()

    # ---- config 1: full-trace replay through the host transaction layer ----
    n_replay = env_int("BENCH_REPLAY_EDITS", len(trace))
    doc = AutoDoc(actor=ActorId(bytes([7]) * 16))
    from automerge_tpu.types import ObjType

    tobj = doc.put_object("_root", "text", ObjType.TEXT)
    t0 = time.perf_counter()
    n_ops = W.apply_edits(doc, tobj, trace[:n_replay])
    doc.commit()
    t_replay = time.perf_counter() - t0
    # bulk-ingest variant: the same edits through splice_text_many (the
    # whole replay loop runs in the native edit session)
    doc_b = AutoDoc(actor=ActorId(bytes([8]) * 16))
    tobj_b = doc_b.put_object("_root", "text", ObjType.TEXT)
    t0 = time.perf_counter()
    n_b = doc_b.splice_text_many(tobj_b, trace[:n_replay])
    doc_b.commit()
    t_batch = time.perf_counter() - t0
    results["replay"] = {
        "edits": n_replay,
        "ops": n_ops,
        "seconds": round(t_replay, 3),
        "ops_per_sec": round(n_ops / t_replay, 1),
        "vs_baseline": round(n_ops / t_replay / RUST_PIN_REPLAY, 4),
        "batch_ops_per_sec": round(n_b / t_batch, 1),
        "batch_vs_baseline": round(n_b / t_batch / RUST_PIN_REPLAY, 4),
    }
    if env_flag("BENCH_PHASES"):
        # the reference edit-trace binary's phase report
        # (rust/edit-trace/src/main.rs:23-55): save / load / fork_at / text
        t0 = time.perf_counter()
        saved = doc_b.save()
        t_save = time.perf_counter() - t0
        t0 = time.perf_counter()
        loaded = AutoDoc.load(saved)
        t_load = time.perf_counter() - t0
        heads = doc_b.get_heads()
        t0 = time.perf_counter()
        forked = doc_b.fork_at(heads)
        t_fork = time.perf_counter() - t0
        t0 = time.perf_counter()
        txt = loaded.text(tobj_b)
        t_text = time.perf_counter() - t0
        assert forked.get_heads() == heads
        results["replay"]["phases_ms"] = {
            "save": round(t_save * 1000, 1),
            "load": round(t_load * 1000, 1),
            "fork_at": round(t_fork * 1000, 1),
            "text": round(t_text * 1000, 1),
            "save_bytes": len(saved),
            "text_len": len(txt),
        }
    note(f"replay: {results['replay']}")
    wall_mark("replay")
    del doc, doc_b

    # ---- config 2: N-way fan-in merge (primary) ----------------------------
    # BASELINE.json sizes: forks of the FULL 259,778-edit trace document
    base_edits = env_int("BENCH_BASE_EDITS", len(trace))
    n_replicas = env_int("BENCH_REPLICAS", 1024)
    fork_edits = env_int("BENCH_FORK_EDITS", 250)
    t0 = time.perf_counter()
    base = W.build_base(trace, base_edits)
    t_base = time.perf_counter() - t0
    t0 = time.perf_counter()
    replica_changes = W.synth_fanin(base, trace, n_replicas, fork_edits, base_edits)
    changes = list(base.changes) + replica_changes
    t_synth = time.perf_counter() - t0
    note(f"fanin build: base {t_base:.1f}s, synth {t_synth:.1f}s")

    # device path: extraction + kernel + native linearization + readback
    def device_merge_timed(chs, reps, rep_times=None):
        """Warm up (jit compile + page-in), then min-of-reps end to end.
        ``rep_times`` (a list, if given) collects every rep's e2e seconds
        so configs can report their spread."""
        log = OpLog.from_changes(chs)
        kw = dict(
            fetch=DeviceDoc.READ_FETCH, n_objs=log.n_objs,
            n_props=len(log.props),
        )
        res = merge_columns(log.columns(), **kw)
        best = (float("inf"), float("inf"))
        for _ in range(reps):
            # release the previous rep's arrays BEFORE reallocating: the
            # tuned allocator (native._tune_allocator) then reuses the
            # same resident pages and identical reps agree within a few
            # percent (the r4 "3-60s" spread was refaulting the working
            # set while the old copy was still live)
            log = res = None
            t0 = time.perf_counter()
            log = OpLog.from_changes(chs)
            t_ex = time.perf_counter() - t0
            t0 = time.perf_counter()
            res = merge_columns(log.columns(), **kw)
            t_mg = time.perf_counter() - t0
            if rep_times is not None:
                rep_times.append(t_ex + t_mg)
            if t_ex + t_mg < sum(best):
                best = (t_ex, t_mg)
        return log, res, best

    log, res, (t_extract, t_merge) = device_merge_timed(
        changes, reps
    )
    t_device = t_extract + t_merge
    n = log.n

    # baseline 1: native sequential apply (measured)
    t_native, native_text = W.seq_apply_baseline(
        changes, base.text_obj, reps=reps
    )
    native_rate = n / t_native

    # convergence check: device == native sequential
    dev = DeviceDoc(log, res)
    dev_text = dev.text(base.text_exid)
    assert dev_text == native_text, "device/native merge divergence"

    # baseline 2: the framework's own host python apply (rate from a slice)
    host_cap = env_int("BENCH_HOST_CAP", 60_000)
    host = Document(ActorId(bytes([9]) * 16))
    t0 = time.perf_counter()
    applied_ops = 0
    for ch in changes:
        host.apply_changes([ch])
        applied_ops += len(ch.ops)
        if applied_ops >= host_cap:
            break
    host.ops  # noqa: B018 — applies defer; materialize the view
    t_host = time.perf_counter() - t0
    host_rate = applied_ops / t_host

    baseline_rate = max(native_rate, RUST_PIN_APPLY)
    dev_rate = n / t_device

    # kernel-only, device-timed: inputs resident on device, outputs left on
    # device (block_until_ready), transport excluded — the number the
    # tunnel tax otherwise obscures. Bytes each way are recorded alongside
    # so the e2e gap is attributable.
    kernel = {}
    # a tunnel stall / compile failure on the remote device must degrade
    # to "no kernel numbers", never kill the whole report (the host-engine
    # headline is the primary metric)
    try:
        if env_flag("BENCH_KERNEL", "1") != "0":
            import jax
            import jax.numpy as jnp

            from automerge_tpu.ops.merge import (
                encode_transport, merge_kernel, merge_kernel_core,
                scatter_geometry_ok, scatter_kernel_core,
            )

            cols_np = log.padded_columns(include_aorder=True)
            cols_dev = jax.block_until_ready(
                {k: jnp.asarray(v) for k, v in cols_np.items()}
            )
            # block_until_ready is not a reliable completion barrier on every
            # remote backend (observed returning in ~0.1ms through the tunnel),
            # so completion is forced by reading ONE scalar back; the link RTT
            # that costs is measured separately and subtracted, and M chained
            # kernel launches amortize the residual.
            M = env_int("BENCH_KERNEL_CHAIN", 4)

            def _sync(o):
                return float(np.asarray(o["obj_vis_len"][0]))

            def time_kernel(fn, host_work=None):
                """Warm + rtt-probe + best-of-reps of M chained launches;
                ``host_work`` (if given) runs between dispatch and sync each
                launch — the host-overlap the production pipeline uses."""
                out = fn(cols_dev)  # compile + warm
                _sync(out)
                t0 = time.perf_counter()
                _sync(out)
                rtt = time.perf_counter() - t0
                t_best = float("inf")
                for _ in range(reps + 1):
                    t0 = time.perf_counter()
                    for _ in range(M):
                        out = fn(cols_dev)  # async dispatch
                        if host_work is not None:
                            host_work()
                    _sync(out)
                    dt = max(time.perf_counter() - t0 - rtt, 1e-9) / M
                    t_best = min(t_best, dt)
                return t_best, rtt

            have_scatter = scatter_geometry_ok(
                len(cols_np["action"]), log.n_objs, len(log.props)
            )
            # all-device document ordering: the chain-condensed kernel
            # (runs found by scans, doubling only over the run tables)
            # replaces the plain pointer-doubling ranking when the run count
            # fits a bucket meaningfully below the row space
            from automerge_tpu.ops.merge import (
                condensed_caps, merge_kernel_condensed,
            )

            rcap, obj_cap = condensed_caps(log)
            if rcap <= len(cols_np["action"]):
                full_fn = merge_kernel_condensed(rcap, obj_cap)
                kernel["condensed_runs"] = int(log.condensed_run_count())
            else:
                full_fn = merge_kernel
            variants = [("full", full_fn), ("core", merge_kernel_core)]
            if have_scatter:
                variants.append(
                    ("scatter", scatter_kernel_core(log.n_objs, len(log.props)))
                )
            for name, fn in variants:
                t_best, rtt = time_kernel(fn)
                kernel[f"t_kernel_{name}_s"] = round(t_best, 4)
                kernel[f"kernel_{name}_ops_per_sec"] = round(n / t_best, 1)
                # per-variant: each variant's timing subtracts its own probe
                kernel[f"sync_rtt_{name}_s"] = round(rtt, 4)
            kernel["kernel_chain"] = M
            _, arrays = encode_transport(cols_np)
            kernel["transport_bytes_in"] = int(
                sum(a.nbytes for a in arrays.values())
            )
            # "pipeline": what production actually runs — the resolution
            # kernel on device OVERLAPPED with the host preorder ranking
            # (ops/merge.py host_linearize supplies elem_index). This number
            # INCLUDES document ordering, unlike the scatter/core variants,
            # and is the reported kernel number.
            from automerge_tpu.ops.oplog import host_linearize

            pipe_fn = variants[-1][1] if have_scatter else merge_kernel_core
            t_best, rtt = time_kernel(
                pipe_fn, host_work=lambda: host_linearize(cols_np)
            )
            kernel["t_kernel_pipeline_s"] = round(t_best, 4)
            kernel["kernel_pipeline_ops_per_sec"] = round(n / t_best, 1)
            kernel["sync_rtt_pipeline_s"] = round(rtt, 4)
            # headline kernel number = the pipeline (resolution + ordering).
            # The scatter/core variants above isolate the device resolution
            # phase; "full" is the all-device path whose ranking gathers are
            # the known-weak spot (BASELINE.md).
            best_core = kernel["kernel_pipeline_ops_per_sec"]
            kernel["kernel_ops_per_sec"] = best_core
            kernel["kernel_vs_baseline"] = round(best_core / baseline_rate, 3)
            note(f"fanin kernel-only: {kernel}")
    except Exception as e:  # noqa: BLE001 — degrade, record, continue
        import traceback

        tb = traceback.format_exc()
        kernel = {"kernel_error": (repr(e) + " | " + tb.splitlines()[-3:][0])[:500]}
        print(f"fanin kernel section failed:\n{tb}", file=sys.stderr, flush=True)

    # ---- device e2e sidecar: the SAME fan-in with the host engine off ----
    # (AUTOMERGE_TPU_HOST_MERGE_MAX=0 -> merge_columns routes to the
    # accelerator). Two numbers: the measured e2e through THIS
    # environment's tunnel (transport-taxed, see BASELINE.md), and a
    # modeled PCIe-attached-host e2e = extract + pipeline kernel +
    # transport bytes at PCIe gen4 x16 (~16 GB/s effective DMA) — the
    # cost the same code pays on a directly-attached accelerator.
    device_e2e = {}
    try:
        if (
            env_flag("BENCH_DEVICE_E2E", "1") != "0"
            and kernel
            and "kernel_error" not in kernel
        ):
            prev = os.environ.get("AUTOMERGE_TPU_HOST_MERGE_MAX")
            os.environ["AUTOMERGE_TPU_HOST_MERGE_MAX"] = "0"
            try:
                _, _, (t_dex, t_dmg) = device_merge_timed(
                    changes, reps
                )
            finally:
                if prev is None:
                    del os.environ["AUTOMERGE_TPU_HOST_MERGE_MAX"]
                else:
                    os.environ["AUTOMERGE_TPU_HOST_MERGE_MAX"] = prev
            t_de2e = t_dex + t_dmg
            pcie_bw = float(env_flag("BENCH_PCIE_BW", 16e9))
            # readback: the READ_FETCH outputs (visible u8 + winner/conflicts/
            # elem_index i32 per row, plus two i32 per object)
            bytes_out = n * (1 + 4 + 4 + 4) + 2 * 4 * (log.n_objs + 2)
            t_model = (
                t_extract
                + kernel["t_kernel_pipeline_s"]
                + (kernel["transport_bytes_in"] + bytes_out) / pcie_bw
            )
            device_e2e = {
                "transport_bytes_out": bytes_out,
                "device_e2e_s": round(t_de2e, 4),
                "device_e2e_ops_per_sec": round(n / t_de2e, 1),
                "device_e2e_vs_pin": round(n / t_de2e / RUST_PIN_APPLY, 3),
                "modeled_pcie_e2e_s": round(t_model, 4),
                "modeled_pcie_ops_per_sec": round(n / t_model, 1),
                "modeled_pcie_vs_pin": round(n / t_model / RUST_PIN_APPLY, 3),
                "modeled_pcie_bw_bytes_per_s": pcie_bw,
            }
            note(f"fanin device e2e: {device_e2e}")
    except Exception as e:  # noqa: BLE001
        import traceback

        tb = traceback.format_exc()
        device_e2e = {"device_e2e_error": repr(e)[:500]}
        print(f"fanin device e2e failed:\n{tb}", file=sys.stderr, flush=True)

    results["fanin"] = {
        **kernel,
        "fanin_device_e2e": device_e2e,
        "replicas": n_replicas,
        "ops": n,
        "t_extract_s": round(t_extract, 3),
        "t_merge_s": round(t_merge, 3),
        "p50_merge_latency_s": round(t_device, 3),
        "ops_per_sec": round(dev_rate, 1),
        "native_seq_apply_ops_per_sec": round(native_rate, 1),
        "host_python_ops_per_sec": round(host_rate, 1),
        "baseline_ops_per_sec": round(baseline_rate, 1),
        # vs the measured decode+apply model (conservative: the model is
        # faster than the Rust reference — no B-tree, no index upkeep)
        "vs_baseline": round(dev_rate / baseline_rate, 3),
        # vs the pinned Rust apply_changes estimate (BASELINE.md) — the
        # divisor BASELINE.json's >=50x target is phrased against
        "vs_pin": round(dev_rate / RUST_PIN_APPLY, 3),
    }
    note(f"fanin: {results['fanin']}")
    wall_mark("fanin")

    # ---- config 2b: incremental device merge (persistent DeviceDoc) --------
    # K small deltas (one live replica typing against a large resident doc)
    # applied through the incremental append + dirty-set re-resolution path;
    # the divisor is the from-scratch extract+resolve at the SAME final
    # state. p50 per-delta latency is the headline (the first delta pays the
    # new-actor rank remap; the median is the steady state the sync path
    # sees). Device-phase spans (trace.time) are exported as phases_s.
    from automerge_tpu import obs
    from automerge_tpu import trace as T

    def _latency_percentiles(hist_name, latencies):
        """Feed raw per-iteration latencies into the named obs histogram
        and report its log-bucket-derived p50/p95/p99 (what a scraper of
        the Prometheus exposition would compute)."""
        h = obs.registry.histogram(hist_name)
        for x in latencies:
            h.observe(x)
        return {
            "latency_p50_s": round(h.percentile(0.50), 6),
            "latency_p95_s": round(h.percentile(0.95), 6),
            "latency_p99_s": round(h.percentile(0.99), 6),
        }

    inc_k = env_int("BENCH_INC_DELTAS", 16)
    inc_ops = env_int("BENCH_INC_OPS", 250)
    inc = {}
    try:
        deltas = W.synth_delta_chain(base, trace, inc_k, inc_ops, base_edits)
        resident_changes = list(base.changes)
        final_changes = resident_changes + [c for b in deltas for c in b]
        _, _, (t_fex, t_fmg) = device_merge_timed(final_changes, reps)
        t_scratch = t_fex + t_fmg
        dev = DeviceDoc.resolve(OpLog.from_changes(resident_changes))
        # clean per-config phase attribution WITHOUT losing the whole-run
        # totals the top-level trace_timings reports: stash + merge back
        saved_timings = {k: list(v) for k, v in T.timings.items()}
        T.reset_timers()
        lats = []
        for b in deltas:
            t0 = time.perf_counter()
            dev.apply_changes(b)
            lats.append(time.perf_counter() - t0)
        full = DeviceDoc.resolve(OpLog.from_changes(final_changes))
        assert dev.text(base.text_exid) == full.text(base.text_exid), (
            "incremental/full divergence"
        )
        lat = sorted(lats)
        p50 = lat[len(lat) // 2]
        delta_ops = sum(len(c.ops) for b in deltas for c in b) / max(
            len(deltas), 1
        )
        inc = {
            "deltas": len(deltas),
            "ops_per_delta": int(delta_ops),
            "resident_ops": dev.log.n,
            "p50_delta_latency_s": round(p50, 5),
            "max_delta_latency_s": round(lat[-1], 5),
            **_latency_percentiles("bench.incremental.delta_latency", lats),
            "delta_ops_per_sec": round(delta_ops / p50, 1),
            "from_scratch_s": round(t_scratch, 4),
            "speedup_vs_rebuild": round(t_scratch / p50, 2),
            "phases_s": {
                k: v["s"] for k, v in T.timing_summary().items()
            },
            "counters": {
                k: v
                for k, v in T.counters.items()
                if k.startswith(("oplog.", "device.", "extract."))
            },
        }
        for k, v in T.timings.items():
            s = saved_timings.setdefault(k, [0.0, 0])
            s[0] += v[0]
            s[1] += v[1]
        T.timings.clear()
        T.timings.update(saved_timings)
        del dev, full, deltas, final_changes
    except Exception as e:  # noqa: BLE001 — degrade, record, continue
        import traceback

        tb = traceback.format_exc()
        inc = {"incremental_error": repr(e)[:500]}
        print(f"incremental config failed:\n{tb}", file=sys.stderr, flush=True)
    results["incremental"] = inc
    note(f"incremental: {results['incremental']}")
    wall_mark("incremental")

    # ---- config 3: Map+Counter commutative merge ---------------------------
    # BASELINE.json size: 10k actors x 1k increments = ~10M ops
    mc_actors = env_int("BENCH_MC_ACTORS", 10_000)
    mc_incs = env_int("BENCH_MC_INCS", 1_000)
    cdoc, keys = W.build_counter_base(64)
    t0 = time.perf_counter()
    mc_changes, mc_expected = W.synth_mapcounter(cdoc, keys, mc_actors, mc_incs)
    t_synth = time.perf_counter() - t0
    all_mc = [a.stored for a in cdoc.doc.history] + mc_changes
    mc_reps = []
    mlog, mres, (t_mc_ex, t_mc_mg) = device_merge_timed(
        all_mc, reps, rep_times=mc_reps
    )
    t_mc = t_mc_ex + t_mc_mg
    mdev = DeviceDoc(mlog, mres)
    # exact-total verification: every increment is +1
    for k in keys[:4]:
        got = mdev.get("_root", k)
        assert got[0] == ("counter", mc_expected.get(k, 0)), (k, got)
    mc_rate = mlog.n / t_mc
    results["mapcounter"] = {
        "actors": mc_actors,
        "ops": mlog.n,
        "t_synth_s": round(t_synth, 2),
        "t_extract_s": round(t_mc_ex, 3),
        "t_merge_s": round(t_mc_mg, 3),
        "p50_merge_latency_s": round(t_mc, 3),
        # per-rep spread: identical calls should agree (VERDICT r4 flagged
        # 3-60s swings; the allocator tuning in native.load targets this)
        "rep_seconds": [round(t, 3) for t in mc_reps],
        "rep_spread": round(max(mc_reps) / min(mc_reps), 2) if mc_reps else None,
        "ops_per_sec": round(mc_rate, 1),
        "vs_baseline": round(mc_rate / RUST_PIN_APPLY, 3),
    }
    note(f"mapcounter: {results['mapcounter']}")
    wall_mark("mapcounter")
    del mlog, mres, mdev, mc_changes, all_mc

    # ---- config 4: RGA stress ---------------------------------------------
    # >=1M interleaved ops on one shared sequence (1k actors x 1k ops)
    rga_actors = env_int("BENCH_RGA_ACTORS", 1_000)
    rga_ops = env_int("BENCH_RGA_OPS", 1_000)
    rbase = W.build_base(trace, 3_000)
    rga_changes = W.synth_rga(rbase, rga_actors, rga_ops)
    all_rga = list(rbase.changes) + rga_changes
    rlog, rres, (t_rga_ex, t_rga_mg) = device_merge_timed(
        all_rga, reps
    )
    t_rga = t_rga_ex + t_rga_mg
    t_rn, rn_text = W.seq_apply_baseline(
        all_rga, rbase.text_obj, reps=reps
    )
    rdev = DeviceDoc(rlog, rres)
    assert rdev.text(rbase.text_exid) == rn_text, "rga device/native divergence"
    rga_baseline = max(rlog.n / t_rn, RUST_PIN_APPLY)
    rga_rate = rlog.n / t_rga
    results["rga"] = {
        "actors": rga_actors,
        "ops": rlog.n,
        "p50_merge_latency_s": round(t_rga, 3),
        "ops_per_sec": round(rga_rate, 1),
        "native_seq_apply_ops_per_sec": round(rlog.n / t_rn, 1),
        "vs_baseline": round(rga_rate / rga_baseline, 3),
        "vs_pin": round(rga_rate / RUST_PIN_APPLY, 3),
    }
    note(f"rga: {results['rga']}")
    wall_mark("rga")
    del rlog, rres, rdev, rga_changes, all_rga

    # ---- config 5: sync catch-up ------------------------------------------
    # BASELINE.json size: 1M-op divergence
    sync_ops = env_int("BENCH_SYNC_OPS", 1_000_000)
    sbase = W.build_base(trace, 2_000)
    n_sync_replicas = max(sync_ops // 2_000, 1)
    sync_changes = W.synth_fanin(sbase, trace, n_sync_replicas, 2_000, 2_000)
    base_save = sbase.doc.save()
    ahead = AutoDoc.load(base_save)
    ahead.apply_changes(sync_changes)
    n_synced = sum(len(c.ops) for c in sync_changes)
    ahead_text = ahead.text(sbase.text_exid)

    def sync_once():
        """One full catch-up of a fresh behind replica; returns
        (seconds, rounds, phase dict). Phases: generate (bloom build,
        have/need, change selection, transport encode) and receive
        (transport decode, causal merge) per side, plus the caught-up
        read that materializes the replica."""
        behind = AutoDoc.load(base_save)
        s1, s2 = SyncState(), SyncState()
        ph = {"gen_ahead": 0.0, "gen_behind": 0.0,
              "recv_behind": 0.0, "recv_ahead": 0.0, "read": 0.0}
        round_lats = []
        t0 = time.perf_counter()
        rounds = 0
        while True:
            t = r0 = time.perf_counter()
            m1 = ahead.generate_sync_message(s1)
            ph["gen_ahead"] += time.perf_counter() - t
            t = time.perf_counter()
            m2 = behind.generate_sync_message(s2)
            ph["gen_behind"] += time.perf_counter() - t
            if m1 is None and m2 is None:
                break
            if m1 is not None:
                t = time.perf_counter()
                behind.receive_sync_message(s2, m1)
                ph["recv_behind"] += time.perf_counter() - t
            if m2 is not None:
                t = time.perf_counter()
                ahead.receive_sync_message(s1, m2)
                ph["recv_ahead"] += time.perf_counter() - t
            rounds += 1
            round_lats.append(time.perf_counter() - r0)
            if rounds > 100:
                raise RuntimeError("sync did not converge")
        # one read inside the timed region: op-store materialization is
        # lazy, so catch-up isn't "done" until the replica is readable
        t = time.perf_counter()
        behind_text = behind.text(sbase.text_exid)
        ph["read"] = time.perf_counter() - t
        dt = time.perf_counter() - t0
        assert behind.get_heads() == ahead.get_heads()
        assert behind_text == ahead_text
        return dt, rounds, ph, round_lats

    # best-of-reps like every other config (a fresh replica per rep);
    # per-round latencies from EVERY rep feed the histogram (the spread
    # is the signal — best-of hides the tail)
    all_round_lats = []
    t_sync, rounds, phases, rl = sync_once()
    all_round_lats.extend(rl)
    for _ in range(reps - 1):
        dt, r, p, rl = sync_once()
        all_round_lats.extend(rl)
        if dt < t_sync:
            t_sync, rounds, phases = dt, r, p
    sync_rate = n_synced / t_sync
    results["sync"] = {
        "divergence_ops": n_synced,
        "rounds": rounds,
        "seconds": round(t_sync, 3),
        "phases_s": {k: round(v, 3) for k, v in phases.items()},
        **_latency_percentiles("bench.sync.round_latency", all_round_lats),
        "ops_per_sec": round(sync_rate, 1),
        "vs_baseline": round(sync_rate / RUST_PIN_APPLY, 4),
    }
    note(f"sync: {results['sync']}")
    wall_mark("sync")

    # ---- micro-bench guard: map put/save/load/apply + range iteration ------
    # (reference: rust/automerge/benches/map.rs:48-263, benches/range.rs —
    # the per-op paths the macro configs cannot isolate; regressions here
    # show up as per-op time even when the batched merge path is healthy)
    micro = {}
    micro_max = env_int("BENCH_MICRO_MAX", 10_000)
    for n_keys in (100, 1_000, 10_000):
        if n_keys > micro_max:
            continue
        t_put = t_save = t_load = t_apply = float("inf")
        for _ in range(max(reps, 1)):
            mdoc = AutoDoc(actor=ActorId(bytes([11]) * 16))
            t0 = time.perf_counter()
            for i in range(n_keys):
                mdoc.put("_root", f"k{i:06}", i)
            mdoc.commit()
            t_put = min(t_put, time.perf_counter() - t0)
            t0 = time.perf_counter()
            saved = mdoc.save()
            t_save = min(t_save, time.perf_counter() - t0)
            t0 = time.perf_counter()
            loaded = AutoDoc.load(saved)
            loaded.keys()  # materialization is lazy; end at readable
            t_load = min(t_load, time.perf_counter() - t0)
            changes = b"".join(
                a.stored.raw_bytes for a in mdoc.doc.history
            )
            rcv = AutoDoc(actor=ActorId(bytes([12]) * 16))
            t0 = time.perf_counter()
            rcv.load_incremental(changes)
            rcv.keys()
            t_apply = min(t_apply, time.perf_counter() - t0)
        micro[f"map_{n_keys}"] = {
            "put_ops_per_sec": round(n_keys / t_put, 1),
            "save_ms": round(t_save * 1000, 2),
            "load_ms": round(t_load * 1000, 2),
            "apply_ops_per_sec": round(n_keys / t_apply, 1),
        }
    # range iteration (benches/range.rs)
    n_range = min(10_000, micro_max)
    rdoc = AutoDoc(actor=ActorId(bytes([13]) * 16))
    lst = rdoc.put_object("_root", "l", ObjType.LIST)
    for i in range(n_range):
        rdoc.insert(lst, i, i)
    rdoc.commit()
    t_range = float("inf")
    for _ in range(max(reps, 1)):
        t0 = time.perf_counter()
        total = sum(1 for _ in rdoc.list_items(lst))
        t_range = min(t_range, time.perf_counter() - t0)
        assert total == n_range
    micro[f"range_{n_range}"] = {
        "iter_elems_per_sec": round(n_range / t_range, 1),
    }
    results["micro"] = micro
    note(f"micro: {micro}")
    wall_mark("micro")

    # ---- config: durable write path (journal + compaction + recovery) ------
    # N commits through a DurableDocument: journal append overhead per
    # commit, compaction count at the default thresholds, and — the
    # recovery-time headline — a reopen that replays snapshot + journal.
    # Counters/timings (journal.append/fsync, compact.*,
    # journal.replayed_records) surface in the JSON for observability.
    import shutil
    import tempfile

    dur = {}
    n_dur = env_int("BENCH_DURABLE_COMMITS", 2000)
    dur_fsync = env_flag("BENCH_DURABLE_FSYNC", "interval")
    tmpd = tempfile.mkdtemp(prefix="amtpu_bench_durable_")
    try:
        dd = AutoDoc.open(
            os.path.join(tmpd, "doc"), fsync=dur_fsync,
            actor=ActorId(bytes([14]) * 16),
        )
        commit_lats = []
        t0 = time.perf_counter()
        for i in range(n_dur):
            c0 = time.perf_counter()
            dd.put("_root", f"k{i % 512:04}", i)
            dd.commit()
            commit_lats.append(time.perf_counter() - c0)
        t_commits = time.perf_counter() - t0
        dd.close()
        compactions = T.counters.get("compact.runs", 0)
        tj = T.timing_summary()
        pre_replayed = T.counters.get("journal.replayed_records", 0)
        t0 = time.perf_counter()
        dd2 = AutoDoc.open(os.path.join(tmpd, "doc"))
        t_reopen = time.perf_counter() - t0
        replayed = T.counters.get("journal.replayed_records", 0) - pre_replayed
        n_history = len(dd2.doc.history)
        dd2.close()
        dur = {
            "commits": n_dur,
            "fsync": dur_fsync,
            "commits_per_sec": round(n_dur / t_commits, 1),
            **_latency_percentiles("bench.durable.commit_latency", commit_lats),
            "journal_append_s": tj.get("journal.append", {}).get("s", 0.0),
            "journal_fsync_s": tj.get("journal.fsync", {}).get("s", 0.0),
            "compactions": compactions,
            "reopen_s": round(t_reopen, 4),
            "replayed_records": replayed,
            "history_after_reopen": n_history,
        }
        assert replayed < n_dur or compactions == 0, dur  # replay is bounded
    except Exception as e:  # noqa: BLE001 — degrade, record, continue
        import traceback

        tb = traceback.format_exc()
        dur = {"durable_error": repr(e)[:500]}
        print(f"durable config failed:\n{tb}", file=sys.stderr, flush=True)
    finally:
        shutil.rmtree(tmpd, ignore_errors=True)
    results["durable"] = dur
    note(f"durable: {results['durable']}")
    wall_mark("durable")

    # ---- config: concurrent serving (socket transport + doc shards) --------
    # The serving-layer headline: N concurrent socket clients pipeline a
    # mixed ingestion workload (applyChanges blobs + put/commit + sync
    # rounds, durable docs, fsync=always) against `rpc --socket`, vs the
    # SAME per-client workload request/response through the serial stdio
    # frontend. Both servers are real subprocesses (their own GIL, as
    # deployed). The structural win: the stdio loop pays one fsync per
    # durable ack, the concurrent server drains each pipelined flight
    # into ONE group-commit fsync and runs distinct docs' fsyncs in
    # parallel. Serial and concurrent reps interleave in tight pairs and
    # the reported speedup is the best PAIRED ratio — on shared
    # infrastructure the fsync/CPU regime drifts minute to minute, and a
    # pair measured in the same window is the honest comparison.
    # Client-observed per-ack latencies feed an obs histogram so
    # p50/p95/p99 are log-bucket-derived like every other config.
    serve_cfg = {}
    try:
        if env_flag("BENCH_SERVE", "1") != "0":
            import base64
            import re
            import shutil
            import socket as socketmod
            import subprocess
            import tempfile
            import threading

            n_clients = env_int("BENCH_SERVE_CLIENTS", 4)
            n_sv_ops = env_int("BENCH_SERVE_OPS", 48)
            sv_flight = env_int("BENCH_SERVE_PIPELINE", 16)
            sv_reps = env_int("BENCH_SERVE_REPS", max(reps, 2))
            sub_env = dict(os.environ, JAX_PLATFORMS="cpu")

            def build_blobs(ci, tag):
                """Pre-encoded single-commit change chunks — the replica-
                push ingestion stream a sync server absorbs."""
                seed = (hash(tag) & 0x7F) | 1
                src = AutoDoc(actor=ActorId(
                    bytes([seed]) + bytes([101 + ci]) * 15))
                for i in range(n_sv_ops):
                    src.put("_root", f"c{ci}_{i:04}", i)
                    src.commit()
                return [
                    base64.b64encode(a.stored.raw_bytes).decode()
                    for a in src.doc.history
                ]

            def client_workload(pipeline, ci, blobs, lats=None):
                """One client's mixed flights; returns its request count.
                ``lats`` collects the send->ack latency of every response
                in the pipelined flights."""
                nreq = 0

                def c(reqs):
                    nonlocal nreq
                    nreq += len(reqs)
                    return pipeline(reqs, lats)

                dname = f"b{ci}_{abs(hash(blobs[0])) % 10**9}"
                d = c([("openDurable", {"name": dname})])[0]["doc"]
                p = c([("create", {})])[0]["doc"]
                s1 = c([("syncStateNew", {})])[0]["sync"]
                s2 = c([("syncStateNew", {})])[0]["sync"]
                for lo in range(0, n_sv_ops, sv_flight):
                    fl = [
                        ("applyChanges", {"doc": d, "data": blobs[i]})
                        for i in range(lo, min(lo + sv_flight, n_sv_ops))
                    ]
                    fl.append(("put", {"doc": d, "obj": "_root",
                                       "prop": f"p{lo}", "value": lo}))
                    fl.append(("commit", {"doc": d}))
                    c(fl)
                    m1 = c([("generateSyncMessage",
                             {"doc": d, "sync": s1})])[0]
                    if m1 is not None:
                        c([("receiveSyncMessage",
                            {"doc": p, "sync": s2, "data": m1})])
                    m2 = c([("generateSyncMessage",
                             {"doc": p, "sync": s2})])[0]
                    if m2 is not None:
                        c([("receiveSyncMessage",
                            {"doc": d, "sync": s1, "data": m2})])
                c([("free", {"doc": d})])
                return nreq

            def socket_pipeline(sock, f, rid):
                def pipeline(reqs, lats=None):
                    first = rid[0] + 1
                    lines = []
                    for m, p in reqs:
                        rid[0] += 1
                        lines.append(json.dumps(
                            {"id": rid[0], "method": m, "params": p}))
                    t0 = time.perf_counter()
                    sock.sendall(("\n".join(lines) + "\n").encode())
                    by = {}
                    while len(by) < len(reqs):
                        resp = json.loads(f.readline())
                        if lats is not None:
                            by_now = time.perf_counter()
                            lats.append(by_now - t0)
                        assert "error" not in resp, resp
                        by[resp["id"]] = resp.get("result")
                    return [by[first + i] for i in range(len(reqs))]
                return pipeline

            # -- the two server subprocesses, started and warmed once ----
            tmp_ser = tempfile.mkdtemp(prefix="amtpu_bench_serve_ser_")
            tmp_conc = tempfile.mkdtemp(prefix="amtpu_bench_serve_conc_")
            ser_proc = conc_proc = None

            srid = [0]

            def serial_request(method, params):
                srid[0] += 1
                ser_proc.stdin.write(json.dumps(
                    {"id": srid[0], "method": method, "params": params}
                ) + "\n")
                ser_proc.stdin.flush()
                resp = json.loads(ser_proc.stdout.readline())
                assert "error" not in resp, resp
                return resp.get("result")

            def serial_sync_pipeline(reqs, lats=None):
                # the stdio embedder protocol: one request, one response
                return [serial_request(m, p) for m, p in reqs]

            def conc_client(ci, blobs, counts, lat_sink, barrier):
                sock = socketmod.create_connection(("127.0.0.1", conc_port))
                sock.setsockopt(socketmod.IPPROTO_TCP,
                                socketmod.TCP_NODELAY, 1)
                f = sock.makefile("r")
                barrier.wait()
                counts[ci] = client_workload(
                    socket_pipeline(sock, f, [0]), ci, blobs, lat_sink)
                sock.close()

            def conc_rep(tag):
                all_blobs = [build_blobs(ci, tag) for ci in range(n_clients)]
                counts = [0] * n_clients
                lat_sinks = [[] for _ in range(n_clients)]
                barrier = threading.Barrier(n_clients + 1)
                ts = [
                    threading.Thread(target=conc_client, args=(
                        ci, all_blobs[ci], counts, lat_sinks[ci], barrier))
                    for ci in range(n_clients)
                ]
                for t in ts:
                    t.start()
                barrier.wait()
                t0 = time.perf_counter()
                for t in ts:
                    t.join()
                dt = time.perf_counter() - t0
                return sum(counts), dt, [x for ls in lat_sinks for x in ls]

            def serial_rep(tag):
                all_blobs = [build_blobs(ci, tag) for ci in range(n_clients)]
                t0 = time.perf_counter()
                n_req = sum(
                    client_workload(serial_sync_pipeline, ci, all_blobs[ci])
                    for ci in range(n_clients)
                )
                return n_req, time.perf_counter() - t0

            try:
                ser_proc = subprocess.Popen(
                    [sys.executable, "-m", "automerge_tpu.rpc",
                     "--durable", tmp_ser],
                    stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                    text=True, env=sub_env,
                )
                conc_proc = subprocess.Popen(
                    [sys.executable, "-m", "automerge_tpu.rpc",
                     "--socket", "127.0.0.1:0", "--durable", tmp_conc],
                    stderr=subprocess.PIPE, text=True, env=sub_env,
                )
                conc_port = int(re.search(
                    r"(\d+)\)", conc_proc.stderr.readline()).group(1))
                # keep draining stderr: a chatty server must not block on
                # a full pipe mid-measurement
                threading.Thread(
                    target=lambda: [None for _ in conc_proc.stderr],
                    daemon=True,
                ).start()

                # warmup both paths (jit/codecs/page-in), untimed
                serial_rep("warm_s")
                conc_rep("warm_c")

                pairs = []
                all_lats = []
                total_req = None
                for rep in range(sv_reps):
                    sn, st = serial_rep(f"s{rep}")
                    cn, ct, lats = conc_rep(f"c{rep}")
                    assert sn == cn, (sn, cn)
                    total_req = cn
                    all_lats.extend(lats)
                    pairs.append((round(sn / st, 1), round(cn / ct, 1)))
                serial_request("shutdown", {})
                ser_proc.stdin.close()
                ser_proc.wait(timeout=60)
                sock = socketmod.create_connection(
                    ("127.0.0.1", conc_port))
                sock.sendall(b'{"id":1,"method":"shutdown"}\n')
                sock.makefile("r").readline()
                sock.close()
                conc_proc.wait(timeout=60)
            finally:
                # a failure mid-config must not leak server processes
                # (their journal flocks) or the temp state directories
                for p_ in (ser_proc, conc_proc):
                    if p_ is not None and p_.poll() is None:
                        p_.kill()
                        p_.wait(timeout=10)
                shutil.rmtree(tmp_ser, ignore_errors=True)
                shutil.rmtree(tmp_conc, ignore_errors=True)

            best_pair = max(pairs, key=lambda p: p[1] / p[0])
            serve_cfg = {
                "clients": n_clients,
                "ops_per_client": n_sv_ops,
                "pipeline_depth": sv_flight,
                "requests": total_req,
                "rep_pairs_rps": [
                    {"serial_stdio": s, "concurrent": c} for s, c in pairs
                ],
                "serial_stdio_requests_per_sec": best_pair[0],
                "requests_per_sec": best_pair[1],
                "speedup_vs_serial": round(best_pair[1] / best_pair[0], 2),
                **_latency_percentiles("bench.serve.request_latency",
                                       all_lats),
            }
    except Exception as e:  # noqa: BLE001 — degrade, record, continue
        import traceback

        tb = traceback.format_exc()
        serve_cfg = {"serve_error": repr(e)[:500]}
        print(f"serve config failed:\n{tb}", file=sys.stderr, flush=True)
    results["serve"] = serve_cfg
    note(f"serve: {results['serve']}")
    wall_mark("serve")

    # ---- config: serve scrub A/B (integrity scrub overhead) ----------------
    # The SAME concurrent socket workload against two fresh servers in
    # tight interleaved pairs: integrity scrub ON at an aggressive
    # cadence (a round every 0.1s, ~150x hotter than the production
    # default) vs AUTOMERGE_TPU_SCRUB=0. The exported goodput_ratio
    # (best paired on/off rps) is the scrub's measured tax on serving
    # goodput; the acceptance floor (>= 0.95 in run_bench_smoke, and a
    # tracked perf_gate metric) enforces the "off the ack path" design —
    # a scrub that grabs doc locks greedily or verifies synchronously
    # lands well under it.
    try:
        if (env_flag("BENCH_SERVE", "1") != "0"
                and env_flag("BENCH_SERVE_SCRUB", "1") != "0"
                and "requests_per_sec" in serve_cfg):
            scrub_reps = env_int("BENCH_SERVE_SCRUB_REPS", sv_reps)
            tmp_on = tempfile.mkdtemp(prefix="amtpu_bench_scrub_on_")
            tmp_off = tempfile.mkdtemp(prefix="amtpu_bench_scrub_off_")
            on_proc = off_proc = None

            def spawn_scrub(tmp, scrub_env):
                proc = subprocess.Popen(
                    [sys.executable, "-m", "automerge_tpu.rpc",
                     "--socket", "127.0.0.1:0", "--durable", tmp],
                    stderr=subprocess.PIPE, text=True,
                    env=dict(sub_env, **scrub_env))
                port = int(re.search(r"(\d+)\)",
                                     proc.stderr.readline()).group(1))
                threading.Thread(target=lambda: [None for _ in proc.stderr],
                                 daemon=True).start()
                return proc, port

            def scrub_rep(port, tag):
                all_blobs = [build_blobs(ci, tag) for ci in range(n_clients)]
                counts = [0] * n_clients
                barrier = threading.Barrier(n_clients + 1)

                def go(ci):
                    sock = socketmod.create_connection(("127.0.0.1", port))
                    sock.setsockopt(socketmod.IPPROTO_TCP,
                                    socketmod.TCP_NODELAY, 1)
                    f = sock.makefile("r")
                    barrier.wait()
                    counts[ci] = client_workload(
                        socket_pipeline(sock, f, [0]), ci, all_blobs[ci])
                    sock.close()

                ts = [threading.Thread(target=go, args=(ci,))
                      for ci in range(n_clients)]
                for t in ts:
                    t.start()
                barrier.wait()
                t0 = time.perf_counter()
                for t in ts:
                    t.join()
                return sum(counts), time.perf_counter() - t0

            try:
                on_proc, on_port = spawn_scrub(tmp_on, {
                    "AUTOMERGE_TPU_SCRUB": "1",
                    "AUTOMERGE_TPU_SCRUB_INTERVAL": "0.1",
                    "AUTOMERGE_TPU_SCRUB_SAMPLE": "64",
                })
                off_proc, off_port = spawn_scrub(
                    tmp_off, {"AUTOMERGE_TPU_SCRUB": "0"})
                scrub_rep(on_port, "warm_on")
                scrub_rep(off_port, "warm_off")
                ratios = []
                for rep in range(scrub_reps):
                    on_n, on_t = scrub_rep(on_port, f"on{rep}")
                    off_n, off_t = scrub_rep(off_port, f"off{rep}")
                    assert on_n == off_n, (on_n, off_n)
                    ratios.append((on_n / on_t) / (off_n / off_t))
                for port in (on_port, off_port):
                    sock = socketmod.create_connection(("127.0.0.1", port))
                    sock.sendall(b'{"id":1,"method":"shutdown"}\n')
                    sock.makefile("r").readline()
                    sock.close()
                on_proc.wait(timeout=60)
                off_proc.wait(timeout=60)
            finally:
                for p_ in (on_proc, off_proc):
                    if p_ is not None and p_.poll() is None:
                        p_.kill()
                        p_.wait(timeout=10)
                shutil.rmtree(tmp_on, ignore_errors=True)
                shutil.rmtree(tmp_off, ignore_errors=True)
            serve_cfg["scrub"] = {
                "reps": scrub_reps,
                "scrub_interval_s": 0.1,
                "rep_goodput_ratios": [round(r, 3) for r in ratios],
                "goodput_ratio": round(max(ratios), 3),
            }
            note(f"serve scrub A/B: {serve_cfg['scrub']}")
    except Exception as e:  # noqa: BLE001 — degrade, record, continue
        import traceback

        print(f"serve scrub config failed:\n{traceback.format_exc()}",
              file=sys.stderr, flush=True)
        serve_cfg["scrub_error"] = repr(e)[:500]

    # ---- config: serve_batched (cross-document batched device merge) -------
    # N resident documents drain one coalesced delta each per cycle — the
    # multi-document work a ShardPool drain hands the device layer. Two
    # modes through the SAME stage/pack/launch machinery (ops/batched.py):
    # per_doc = one packed launch per document (max_docs_per_launch=1, the
    # old dispatch discipline), batched = every document in ONE launch per
    # drain cycle. Kernel launches are counted via the
    # device.kernel_launches{path=batched} counter and asserted to drop
    # from O(docs) to O(1) per cycle; both modes' final documents are
    # checked identical. Each document carries an untouched "archive"
    # ballast object so the drained deltas stay on the dirty-subset path
    # (the serve-shaped workload: big resident history, edits concentrated
    # in the live object).
    sb_cfg = {}
    try:
        if env_flag("BENCH_SERVE_BATCHED", "1") != "0":
            from automerge_tpu.obs import prof
            from automerge_tpu.ops.batched import apply_cross_doc

            sb_docs = env_int("BENCH_SB_DOCS", 32)
            sb_cycles = env_int("BENCH_SB_CYCLES", 8)
            sb_ops = env_int("BENCH_SB_OPS", 40)
            sb_ballast = env_int("BENCH_SB_BALLAST", 4000)

            def sb_launches():
                return obs.counter_values("device.kernel_launches", "path")

            def sb_input_bytes():
                """(kernel input bytes, dense-equivalent bytes) — the
                run-native staging's counters; input = what device_put
                actually moved and the expand+resolve jit consumed."""
                return (
                    obs.counter_values(
                        "device.kernel_input_bytes", "").get("", 0),
                    obs.counter_values(
                        "device.kernel_input_dense_bytes", "").get("", 0),
                )

            def sb_workload(tag):
                """Per doc: (base changes, [delta per cycle]) — one
                editing replica typing into the live object each cycle."""
                wl = []
                for i in range(sb_docs):
                    base = AutoDoc(actor=ActorId(bytes([21]) * 16))
                    live = base.put_object("_root", "live", ObjType.TEXT)
                    base.splice_text(live, 0, 0, "live seed text ")
                    arch = base.put_object("_root", "archive", ObjType.TEXT)
                    base.splice_text(arch, 0, 0, "x" * sb_ballast)
                    base.commit()
                    chs = [a.stored for a in base.doc.history]
                    ed = base.fork(actor=ActorId(
                        bytes([31 + (tag & 1)]) + bytes([i % 250]) + bytes(14)))
                    seen = {c.hash for c in chs}
                    cycles = []
                    for c in range(sb_cycles):
                        ln = ed.length(live)
                        for j in range(sb_ops):
                            ed.splice_text(
                                live, (i + c * sb_ops + j) % max(ln + j, 1),
                                0, "ab"[j % 2],
                            )
                        ed.commit()
                        delta = [
                            a.stored for a in ed.doc.history
                            if a.stored.hash not in seen
                        ]
                        seen.update(ch.hash for ch in delta)
                        cycles.append(delta)
                    wl.append((chs, cycles))
                return wl

            def sb_run(wl, max_per_launch, reports=None, pipeline=None):
                """``reports`` (a list, if given) collects one profiler
                cycle report per drain cycle — the observatory's
                attribution for exactly these drains. ``pipeline``
                forces the drain pipeline on/off (None = env default);
                the per-doc baseline runs with it off so its timing
                keeps the serial per-doc-launch semantics."""
                devs = [
                    DeviceDoc.resolve(OpLog.from_changes(chs))
                    for chs, _ in wl
                ]
                l0 = sb_launches()
                b0 = sb_input_bytes()
                t0 = time.perf_counter()
                for c in range(sb_cycles):
                    with prof.cycle(kind="bench_drain") as cyc:
                        apply_cross_doc(
                            [(devs[i], [wl[i][1][c]])
                             for i in range(sb_docs)],
                            max_docs_per_launch=max_per_launch,
                            pipeline=pipeline,
                        )
                    if reports is not None and cyc.report is not None:
                        reports.append(cyc.report)
                dt = time.perf_counter() - t0
                l1 = sb_launches()
                b1 = sb_input_bytes()
                dl = {
                    k: l1.get(k, 0) - l0.get(k, 0)
                    for k in set(l0) | set(l1)
                    if l1.get(k, 0) != l0.get(k, 0)
                }
                bts = (b1[0] - b0[0], b1[1] - b0[1])
                return devs, dt, dl, bts

            wl = sb_workload(0)
            delta_ops = sum(
                len(c.ops) for _, cycles in wl for b in cycles for c in b
            )
            sb_half = max(sb_docs // 2, 1)
            # warm all three mode shapes (jit compile per capacity bucket)
            sb_run(sb_workload(1), 1, pipeline=False)
            sb_run(sb_workload(1), None)
            sb_run(sb_workload(1), sb_half, pipeline=True)
            t_per = t_bat = t_pipe = float("inf")
            cycle_reports = []
            pipe_reports = []
            rn_bytes = (0, 0)
            for _ in range(max(reps, 1)):
                devs_p, dt_p, l_per, _ = sb_run(wl, 1, pipeline=False)
                devs_b, dt_b, l_bat, bts = sb_run(
                    wl, None, reports=cycle_reports
                )
                # pipelined mode: two half-drain launches per cycle so
                # chunk 2's host staging runs under chunk 1's kernel
                devs_pl, dt_pl, l_pipe, _ = sb_run(
                    wl, sb_half, reports=pipe_reports, pipeline=True
                )
                t_per = min(t_per, dt_p)
                t_bat = min(t_bat, dt_b)
                t_pipe = min(t_pipe, dt_pl)
                rn_bytes = (rn_bytes[0] + bts[0], rn_bytes[1] + bts[1])
            # the observatory's view of the batched drains: >=90% of the
            # measured drain wall clock attributed to named stages, with
            # the host/device split and the pack-site occupancy figure
            cycle_report = prof.summarize_reports(cycle_reports)
            pipe_report = prof.summarize_reports(pipe_reports)
            # all modes must materialize identical documents
            for i in (0, sb_docs // 2, sb_docs - 1):
                assert devs_p[i].hydrate() == devs_b[i].hydrate(), i
                assert devs_pl[i].hydrate() == devs_b[i].hydrate(), i
            sb_cfg = {
                "docs": sb_docs,
                "cycles": sb_cycles,
                "ops_per_delta": sb_ops,
                "delta_ops_total": delta_ops,
                "resident_ops": int(devs_b[0].log.n),
                "per_doc_seconds": round(t_per, 4),
                "per_doc_ops_per_sec": round(delta_ops / t_per, 1),
                "per_doc_launches": l_per,
                "batched_seconds": round(t_bat, 4),
                "batched_ops_per_sec": round(delta_ops / t_bat, 1),
                "batched_launches": l_bat,
                "launches_per_drain_per_doc": round(
                    l_per.get("batched", 0) / sb_cycles, 2
                ),
                "launches_per_drain_batched": round(
                    l_bat.get("batched", 0) / sb_cycles, 2
                ),
                "uplift_vs_per_doc": round(t_per / t_bat, 2),
                "occupancy": cycle_report["occupancy"],
                "cycle_report": cycle_report,
                # run-native staging: what the batched drains actually
                # shipped to (and computed on) the device vs the dense
                # image those rows would have been
                "run_native": {
                    "kernel_input_bytes": int(rn_bytes[0]),
                    "kernel_input_dense_bytes": int(rn_bytes[1]),
                    "input_compress_ratio": round(
                        rn_bytes[1] / rn_bytes[0], 2
                    ) if rn_bytes[0] else 0.0,
                },
                # the double-buffered drain: two half-launches per
                # cycle, second half's host staging under the first
                # half's in-flight kernel
                "pipeline": {
                    "seconds": round(t_pipe, 4),
                    "ops_per_sec": round(delta_ops / t_pipe, 1),
                    "launches_per_drain": round(
                        l_pipe.get("batched", 0) / sb_cycles, 2
                    ),
                    "overlap_s": pipe_report.get("overlap_s", 0.0),
                    "overlap_fraction": pipe_report.get(
                        "overlap_fraction", 0.0
                    ),
                    "uplift_vs_per_doc": round(t_per / t_pipe, 2),
                    "vs_single_launch": round(t_bat / t_pipe, 2),
                },
            }
            del devs_p, devs_b, devs_pl, wl
    except Exception as e:  # noqa: BLE001 — degrade, record, continue
        import traceback

        tb = traceback.format_exc()
        sb_cfg = {"serve_batched_error": repr(e)[:500]}
        print(f"serve_batched config failed:\n{tb}", file=sys.stderr,
              flush=True)
    results["serve_batched"] = sb_cfg
    note(f"serve_batched: {results['serve_batched']}")
    wall_mark("serve_batched")

    # ---- config: cluster (replicated serving + leader failover) ------------
    # Three node subprocesses (leader + 2 followers, quorum acks) behind
    # an in-process router. The workload commits through the router while
    # the leader is kill -9'd BENCH_CLUSTER_FAILOVERS times; each cycle
    # measures the client-observed failover latency (first failed ack ->
    # first successful ack on the promoted leader) and the killed node
    # rejoins as a follower before the next cycle. Reported: replicated
    # commit throughput under quorum acks plus failover-latency
    # p50/p95/p99 from the same log-bucketed histograms as every other
    # config.
    cluster_cfg = {}
    try:
        if env_flag("BENCH_CLUSTER", "1") != "0":
            import re
            import shutil
            import socket as socketmod
            import subprocess
            import tempfile
            import threading

            from automerge_tpu.cluster import ClusterRouter

            n_failovers = env_int("BENCH_CLUSTER_FAILOVERS", 3)
            n_warm = env_int("BENCH_CLUSTER_OPS", 30)
            hb = float(env_flag("BENCH_CLUSTER_HEARTBEAT", "0.25"))
            tmp_cluster = tempfile.mkdtemp(prefix="amtpu_bench_cluster_")
            sub_env = dict(
                os.environ, JAX_PLATFORMS="cpu",
                AUTOMERGE_TPU_CLUSTER_HEARTBEAT=str(hb),
            )
            procs = {}

            def spawn_node(i, extra):
                d = os.path.join(tmp_cluster, f"n{i}")
                os.makedirs(d, exist_ok=True)
                p = subprocess.Popen(
                    [sys.executable, "-m", "automerge_tpu.rpc",
                     "--socket", "127.0.0.1:0", "--durable", d,
                     "--node-id", f"n{i}"] + extra,
                    stderr=subprocess.PIPE, text=True, env=sub_env,
                )
                addr = "127.0.0.1:" + re.search(
                    r"(\d+)\)", p.stderr.readline()).group(1)
                threading.Thread(
                    target=lambda: [None for _ in p.stderr],
                    daemon=True).start()
                procs[addr] = p
                return addr

            a1 = spawn_node(1, ["--follow", "pending", "--ack-replicas", "1"])
            a2 = spawn_node(2, ["--follow", "pending", "--ack-replicas", "1"])
            a0 = spawn_node(0, ["--replicate-to", a1, "--replicate-to", a2,
                                "--ack-replicas", "1"])
            router = ClusterRouter([[a0, a1, a2]], heartbeat=hb,
                                   miss_limit=2)
            router.start()

            # the reference retry client (clients/python): capped-backoff
            # retry on retriable errors with a per-call deadline budget —
            # its blocked-seconds accounting IS the client-observed
            # failover latency, so the bench stops hand-rolling the loop
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "clients", "python"))
            from amtpu_client import RetryingClient

            try:
                c = RetryingClient(router.address, deadline_s=60,
                                   backoff_s=0.02, max_backoff_s=0.2)
                d = c.call("openDurable", name="bench")["doc"]
                # throughput under quorum acks, failure-free
                t0 = time.perf_counter()
                for i in range(n_warm):
                    c.call("put", doc=d, obj="_root", prop=f"w{i}", value=i)
                    c.call("commit", doc=d)
                t_quorum = time.perf_counter() - t0

                fo_lats = []
                k = 0
                for cycle in range(n_failovers):
                    leader = next(
                        g["leader"] for g in c.call("clusterInfo")["groups"])
                    procs[leader].kill()  # SIGKILL: the real thing
                    procs[leader].wait()
                    # first acked write after the kill IS the
                    # client-observed failover latency: wall time covers
                    # both failure modes — requests frozen inside the
                    # router while it promotes, and retriable errors the
                    # retry loop rides out (c.last.blocked_s)
                    t_fail = time.perf_counter()
                    c.call("put", doc=d, obj="_root", prop=f"f{k}", value=k)
                    c.call("commit", doc=d)
                    fo_lats.append(time.perf_counter() - t_fail)
                    k += 1
                    # a fresh node rejoins the group as a follower so
                    # every cycle keeps a full quorum pool
                    new_leader = next(
                        g["leader"] for g in c.call("clusterInfo")["groups"])
                    rejoin = spawn_node(
                        10 + cycle, ["--follow", new_leader,
                                     "--ack-replicas", "1"])
                    c.call("clusterJoin", group=0, addr=rejoin)
                # every acked key must be readable (zero acked-write loss)
                for i in range(n_warm):
                    got = c.call("get", doc=d, obj="_root", prop=f"w{i}")
                    assert got == i, (i, got)
                for i in range(k):
                    got = c.call("get", doc=d, obj="_root", prop=f"f{i}")
                    assert got == i, (i, got)
                c.close()
            finally:
                router.stop()
                for p_ in procs.values():
                    if p_.poll() is None:
                        p_.kill()
                        p_.wait(timeout=10)
                shutil.rmtree(tmp_cluster, ignore_errors=True)

            cluster_cfg = {
                "nodes": 3,
                "ack_replicas": 1,
                "failovers": n_failovers,
                "quorum_commits_per_sec": round(n_warm / t_quorum, 1),
                "failover_latencies_s": [round(x, 3) for x in fo_lats],
                **{
                    k.replace("latency", "failover_latency"): v
                    for k, v in _latency_percentiles(
                        "bench.cluster.failover_latency", fo_lats
                    ).items()
                },
            }
    except Exception as e:  # noqa: BLE001 — degrade, record, continue
        import traceback

        tb = traceback.format_exc()
        cluster_cfg = {"cluster_error": repr(e)[:500]}
        print(f"cluster config failed:\n{tb}", file=sys.stderr, flush=True)
    results["cluster"] = cluster_cfg
    note(f"cluster: {results['cluster']}")
    wall_mark("cluster")

    # ---- config: tiered (bounded-memory residency at many-doc scale) -------
    # N durable documents created and Zipfian-accessed through the REAL
    # socket serve path against two servers: one with the tiered store's
    # budgets configured (bounded residency: idle docs demote warm ->
    # cold, cold docs hydrate on access), one with the store unbounded
    # (the old behavior: every doc ever opened stays fully materialized,
    # run at a reduced doc count because every live journal holds an fd).
    # Asserted here: the store server's RSS stays under the configured
    # watermark while serving every doc, demotions/hydrations actually
    # fired, and a demote -> hydrate round trip returns byte-identical
    # contents. Reported: RSS vs the unbounded server's linear
    # projection, cold-open (hydration) latency percentiles from the
    # server's own store.hydrate histogram, and access throughput.
    tiered_cfg = {}
    try:
        if env_flag("BENCH_TIERED", "1") != "0":
            import re
            import resource
            import shutil
            import socket as socketmod
            import subprocess
            import tempfile
            import threading

            td_docs = env_int("BENCH_TD_DOCS", 100_000)
            td_accesses = env_int("BENCH_TD_ACCESSES",
                                  min(td_docs, 20_000))
            td_flight = env_int("BENCH_TD_PIPELINE", 64)
            td_headroom = env_int("BENCH_TD_RSS_HEADROOM", 256 << 20)

            # the unbounded baseline holds one journal fd per live doc:
            # cap it under the fd limit (raised as far as allowed), then
            # project linearly to td_docs
            soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
            try:
                resource.setrlimit(
                    resource.RLIMIT_NOFILE,
                    (min(hard, 1 << 16) if hard > 0 else 1 << 16, hard))
                soft = resource.getrlimit(resource.RLIMIT_NOFILE)[0]
            except (ValueError, OSError):
                pass
            td_base_docs = env_int(
                "BENCH_TD_BASELINE_DOCS",
                max(64, min(td_docs, 2000, soft - 128)))

            def proc_rss(pid):
                with open(f"/proc/{pid}/statm") as f:
                    return int(f.read().split()[1]) * os.sysconf(
                        "SC_PAGE_SIZE")

            def spawn(tag, extra_env):
                tmp = tempfile.mkdtemp(prefix=f"amtpu_bench_td_{tag}_")
                p = subprocess.Popen(
                    [sys.executable, "-m", "automerge_tpu.rpc",
                     "--socket", "127.0.0.1:0", "--durable", tmp],
                    stderr=subprocess.PIPE, text=True,
                    env=dict(os.environ, JAX_PLATFORMS="cpu", **extra_env),
                )
                port = int(re.search(
                    r"(\d+)\)", p.stderr.readline()).group(1))
                threading.Thread(
                    target=lambda: [None for _ in p.stderr],
                    daemon=True).start()
                sock = socketmod.create_connection(("127.0.0.1", port))
                sock.setsockopt(socketmod.IPPROTO_TCP,
                                socketmod.TCP_NODELAY, 1)
                return p, tmp, sock, sock.makefile("r")

            def flights(sock, f, reqs, lats=None):
                """Pipelined request flights; returns results by order."""
                out_ = []
                for lo in range(0, len(reqs), td_flight):
                    chunk = reqs[lo:lo + td_flight]
                    lines = [
                        json.dumps({"id": lo + i, "method": m, "params": pp})
                        for i, (m, pp) in enumerate(chunk)
                    ]
                    t0 = time.perf_counter()
                    sock.sendall(("\n".join(lines) + "\n").encode())
                    by = {}
                    while len(by) < len(chunk):
                        resp = json.loads(f.readline())
                        err = resp.get("error")
                        if err is not None:
                            if err.get("retriable"):
                                # backpressure/hydration contention: the
                                # client owns the retry
                                m, pp = chunk[resp["id"] - lo]
                                time.sleep(0.01)
                                sock.sendall((json.dumps(
                                    {"id": resp["id"], "method": m,
                                     "params": pp}) + "\n").encode())
                                continue
                            raise AssertionError(resp)
                        if lats is not None:
                            lats.append(time.perf_counter() - t0)
                        by[resp["id"]] = resp.get("result")
                    out_.extend(by[lo + i] for i in range(len(chunk)))
                return out_

            # residency, not durability, is under test: fsync="never"
            # keeps the populate phase from being an fsync benchmark
            # (demote/hydrate correctness is unaffected — the journal
            # bytes are written either way)
            td_fsync = env_flag("BENCH_TD_FSYNC", "never")

            def populate(sock, f, n, tag):
                handles = []
                step = max(1, td_flight // 4)
                for lo in range(0, n, step):
                    batch = range(lo, min(lo + step, n))
                    hs = [
                        r["doc"] for r in flights(sock, f, [
                            ("openDurable",
                             {"name": f"t{i:06}", "fsync": td_fsync})
                            for i in batch
                        ])
                    ]
                    handles.extend(hs)
                    reqs = []
                    for i, h in zip(batch, hs):
                        reqs.append(("put", {"doc": h, "obj": "_root",
                                             "prop": "v", "value": i}))
                        reqs.append(("commit", {"doc": h}))
                    flights(sock, f, reqs)
                return handles

            store_env = {
                "AUTOMERGE_TPU_STORE_WARM_BYTES": str(
                    env_int("BENCH_TD_WARM_BYTES", 4 << 20)),
                "AUTOMERGE_TPU_STORE_EVICT_INTERVAL": "0.2",
                "AUTOMERGE_TPU_STORE_MIN_IDLE": "0.05",
            }
            sp = st = ss = sf = None
            up = ut = us = uf = None
            try:
                sp, st, ss, sf = spawn("store", store_env)
                up, ut, us, uf = spawn("unbounded", {})
                rss_store_0 = proc_rss(sp.pid)
                rss_unb_0 = proc_rss(up.pid)
                rss_budget = rss_store_0 + td_headroom
                # tell the store its hard watermark (config accepts env
                # only at construction, so restart-free: the warm-bytes
                # budget is the active bound; the watermark is asserted
                # on the measured outcome below)

                t0 = time.perf_counter()
                store_handles = populate(ss, sf, td_docs, "s")
                t_pop = time.perf_counter() - t0
                populate(us, uf, td_base_docs, "u")

                rss_store_1 = proc_rss(sp.pid)
                rss_unb_1 = proc_rss(up.pid)
                per_doc = (rss_unb_1 - rss_unb_0) / max(1, td_base_docs)
                rss_linear = rss_unb_0 + per_doc * td_docs

                # Zipfian access phase against the store server
                rng = np.random.default_rng(7)
                draws = rng.zipf(1.3, size=4 * td_accesses)
                draws = draws[draws <= td_docs][:td_accesses]
                while len(draws) < td_accesses:
                    extra = rng.zipf(1.3, size=td_accesses)
                    draws = np.concatenate(
                        [draws, extra[extra <= td_docs]])[:td_accesses]
                lats = []
                t0 = time.perf_counter()
                reqs = [
                    ("get", {"doc": store_handles[int(r) - 1],
                             "obj": "_root", "prop": "v"})
                    for r in draws
                ]
                vals = flights(ss, sf, reqs, lats)
                t_access = time.perf_counter() - t0
                for r, v in zip(draws, vals):
                    assert v == int(r) - 1, (int(r) - 1, v)
                rss_store_2 = proc_rss(sp.pid)

                # demote -> hydrate round trip must be byte-identical
                probe = store_handles[0]
                save_a = flights(ss, sf, [("save", {"doc": probe})])[0]
                flights(ss, sf, [("storeDemote", {"name": "t000000"})])
                save_b = flights(ss, sf, [("save", {"doc": probe})])[0]
                roundtrip_ok = save_a == save_b

                # the server's own accounting: tiers, demotions, hydrate
                # latency histogram
                snap = flights(ss, sf, [("metrics", {"format": "json"})])[0]
                entries = snap["metrics"]
                demotions = sum(
                    e["value"] for e in entries
                    if e["name"] == "store.demotions"
                    and e["type"] == "counter"
                )
                hyd = [
                    e for e in entries
                    if e["name"] == "store.hydrate"
                    and e["type"] == "histogram"
                ]
                hydrations = sum(e["count"] for e in hyd)
                tiers = {
                    e["labels"]["tier"]: e["value"]
                    for e in entries
                    if e["name"] == "store.tier" and e["type"] == "gauge"
                }

                rss_peak = max(rss_store_1, rss_store_2)
                assert rss_peak <= rss_budget, (
                    f"store RSS {rss_peak} exceeded budget {rss_budget}")
                # > 1: at least one POLICY demotion beyond the explicit
                # round-trip storeDemote below — a run where the budget
                # never bites is vacuous
                assert demotions > 1, "no policy demotions fired"
                assert hydrations > 0, "no cold opens fired (vacuous run)"
                assert roundtrip_ok, "demote->hydrate changed the bytes"

                for sock_, f_ in ((ss, sf), (us, uf)):
                    flights(sock_, f_, [("shutdown", {})])
                sp.wait(timeout=60)
                up.wait(timeout=60)
            finally:
                for p_ in (sp, up):
                    if p_ is not None and p_.poll() is None:
                        p_.kill()
                        p_.wait(timeout=10)
                for d_ in (st, ut):
                    if d_ is not None:
                        shutil.rmtree(d_, ignore_errors=True)

            tiered_cfg = {
                "docs": td_docs,
                "accesses": td_accesses,
                "baseline_docs": td_base_docs,
                "populate_seconds": round(t_pop, 3),
                "populate_docs_per_sec": round(td_docs / t_pop, 1),
                "access_seconds": round(t_access, 3),
                "accesses_per_sec": round(td_accesses / t_access, 1),
                "rss_budget_bytes": rss_budget,
                "rss_store_bytes": rss_peak,
                "rss_under_budget": True,
                "rss_unbounded_baseline_bytes": rss_unb_1,
                "rss_linear_projection_bytes": int(rss_linear),
                "bytes_per_resident_doc": int(per_doc),
                "tiers": tiers,
                "demotions": int(demotions),
                "hydrations": int(hydrations),
                "roundtrip_identical": roundtrip_ok,
                **{
                    k.replace("latency", "cold_open_latency"): round(v, 6)
                    for k, v in (
                        ("latency_p50_s", hyd[0]["p50"] if hyd else 0.0),
                        ("latency_p95_s", hyd[0]["p95"] if hyd else 0.0),
                        ("latency_p99_s", hyd[0]["p99"] if hyd else 0.0),
                    )
                },
                **_latency_percentiles("bench.tiered.access_latency", lats),
            }
    except Exception as e:  # noqa: BLE001 — degrade, record, continue
        import traceback

        tb = traceback.format_exc()
        tiered_cfg = {"tiered_error": repr(e)[:500]}
        print(f"tiered config failed:\n{tb}", file=sys.stderr, flush=True)
    results["tiered"] = tiered_cfg
    note(f"tiered: {results['tiered']}")
    wall_mark("tiered")

    # ---- config: compressed (compute-on-compressed resident columns) -------
    # The same synthetic text+counter workload drained through the
    # cross-doc batched path TWICE in one process: compressed residency
    # (AUTOMERGE_TPU_COMPRESSED=1, the default) vs dense (=0, the
    # fallback/oracle mode). Asserted inside the config: bit-identical
    # materialized documents and op columns across modes. Reported: true
    # resident column bytes per doc and h2d bytes per drain under each
    # mode (the device.h2d_bytes counter the staging sites feed), their
    # ratios, and resident-docs-per-GiB — the "5-10x more resident docs
    # per chip" claim as a measured number.
    comp_cfg = {}
    try:
        if env_flag("BENCH_COMPRESSED", "1") != "0":
            from automerge_tpu.ops.batched import apply_cross_doc
            from automerge_tpu.types import ObjType as _OT
            from automerge_tpu.types import ScalarValue

            cp_docs = env_int("BENCH_CP_DOCS", 8)
            cp_cycles = env_int("BENCH_CP_CYCLES", 6)
            cp_ops = env_int("BENCH_CP_OPS", 40)

            wl = []
            for i in range(cp_docs):
                cbase = AutoDoc(actor=ActorId(bytes([41]) * 16))
                live = cbase.put_object("_root", "live", _OT.TEXT)
                cbase.splice_text(live, 0, 0, f"seed text for doc {i} ")
                cbase.put("_root", "ctr", ScalarValue("counter", 0))
                cbase.commit()
                chs = [a.stored for a in cbase.doc.history]
                ed = cbase.fork(actor=ActorId(
                    bytes([51]) + bytes([i % 250]) + bytes(14)))
                seen = {c.hash for c in chs}
                cyc = []
                for c in range(cp_cycles):
                    ln = ed.length(live)
                    for j in range(cp_ops):
                        ed.splice_text(
                            live, (i + c * cp_ops + j) % max(ln + j, 1),
                            0, "ab"[j % 2],
                        )
                    ed.increment("_root", "ctr", 1)
                    ed.commit()
                    delta = [
                        a.stored for a in ed.doc.history
                        if a.stored.hash not in seen
                    ]
                    seen.update(ch.hash for ch in delta)
                    cyc.append(delta)
                wl.append((chs, cyc))

            def cp_run(mode, work):
                prev = os.environ.get("AUTOMERGE_TPU_COMPRESSED")
                os.environ["AUTOMERGE_TPU_COMPRESSED"] = mode
                try:
                    devs = [
                        DeviceDoc.resolve(OpLog.from_changes(chs))
                        for chs, _ in work
                    ]
                    h0 = obs.counter_values(
                        "device.h2d_bytes", "").get("", 0)
                    t0 = time.perf_counter()
                    for c in range(cp_cycles):
                        apply_cross_doc(
                            [(devs[i], [work[i][1][c]])
                             for i in range(len(work))]
                        )
                    dt = time.perf_counter() - t0
                    h1 = obs.counter_values(
                        "device.h2d_bytes", "").get("", 0)
                    col = sum(d.log.resident_column_nbytes() for d in devs)
                    res = sum(d.resident_nbytes() for d in devs)
                    return devs, h1 - h0, col, res, dt
                finally:
                    if prev is None:
                        os.environ.pop("AUTOMERGE_TPU_COMPRESSED", None)
                    else:
                        os.environ["AUTOMERGE_TPU_COMPRESSED"] = prev

            # warm both mode shapes (jit compile + page-in) on a
            # throwaway prefix so the reported seconds compare staging,
            # not first-launch compile
            warm = wl[: max(cp_docs // 2, 1)]
            cp_run("1", warm)
            cp_run("0", warm)
            devs_c, h2d_c, col_c, res_c, t_c = cp_run("1", wl)
            devs_d, h2d_d, col_d, res_d, t_d = cp_run("0", wl)
            # bit-identical materialized documents AND op columns
            for i in (0, cp_docs // 2, cp_docs - 1):
                assert devs_c[i].hydrate() == devs_d[i].hydrate(), i
                for colname in ("id_key", "action", "elem_ref",
                                "obj_dense", "value_int"):
                    assert np.array_equal(
                        np.asarray(getattr(devs_c[i].log, colname)),
                        np.asarray(getattr(devs_d[i].log, colname)),
                    ), (i, colname)
            gib = 1 << 30
            per_doc_c = max(res_c // cp_docs, 1)
            per_doc_d = max(res_d // cp_docs, 1)
            comp_cfg = {
                "docs": cp_docs,
                "cycles": cp_cycles,
                "ops_per_delta": cp_ops,
                "resident_ops": int(devs_c[0].log.n),
                "identical_docs": True,
                "resident_column_bytes_per_doc": col_c // cp_docs,
                "resident_column_bytes_per_doc_dense": col_d // cp_docs,
                "resident_compress_ratio": round(col_d / max(col_c, 1), 2),
                "device_bytes_per_doc": int(per_doc_c),
                "device_bytes_per_doc_dense": int(per_doc_d),
                "h2d_bytes_per_drain": h2d_c // cp_cycles,
                "h2d_bytes_per_drain_dense": h2d_d // cp_cycles,
                "h2d_compress_ratio": round(h2d_d / max(h2d_c, 1), 2),
                "resident_docs_per_gib": int(gib // per_doc_c),
                "resident_docs_per_gib_dense": int(gib // per_doc_d),
                "seconds_compressed": round(t_c, 4),
                "seconds_dense": round(t_d, 4),
            }
            del devs_c, devs_d, wl
    except Exception as e:  # noqa: BLE001 — degrade, record, continue
        import traceback

        tb = traceback.format_exc()
        comp_cfg = {"compressed_error": repr(e)[:500]}
        print(f"compressed config failed:\n{tb}", file=sys.stderr,
              flush=True)
    results["compressed"] = comp_cfg
    note(f"compressed: {results['compressed']}")
    wall_mark("compressed")

    # ---- config: overload (admission control + deadline propagation) -------
    # Drive a concurrent durable server far past its saturation point
    # with per-request deadlines and measure GOODPUT: responses that
    # succeed within their own deadline. Clients are the reference
    # cooperating kind — an AIMD in-flight window that halves on
    # Overloaded/DeadlineExceeded and grows on success — so the
    # admission layer's shed answers act as the congestion signal that
    # parks the system at its efficient operating point. The SAME drive
    # against an admission-disabled control server shows the classic
    # overload collapse: no shed signal, queues to the configured
    # bound, every response late. Also verified in-config: zero
    # acked-write loss (every acked put covered by an acked commit is
    # present at readback) and zero deadlocked clients. Each phase
    # writes fresh documents: doc/journal growth across phases would
    # otherwise confound capacity vs overdrive service times.
    ol_cfg = {}
    try:
        if env_flag("BENCH_OVERLOAD", "1") != "0":
            import re
            import shutil
            import socket as socketmod
            import subprocess
            import tempfile
            import threading

            ol_docs = env_int("BENCH_OL_DOCS", 3)
            # capacity is measured at a healthy queue depth (waits well
            # inside every shed band); overdrive offers OVERDRIVE x
            # that in-flight demand per client
            ol_cap_window = env_int("BENCH_OL_CAP_WINDOW", 16)
            ol_overdrive = env_int("BENCH_OL_OVERDRIVE", 12)
            ol_window = ol_cap_window * ol_overdrive
            ol_cap_ops = env_int("BENCH_OL_CAP_OPS", 3000)
            ol_ops = env_int("BENCH_OL_OPS", 4800)  # overdrive reqs/client
            ol_deadline_ms = env_int("BENCH_OL_DEADLINE_MS", 200)
            ol_env = dict(
                os.environ,
                JAX_PLATFORMS="cpu",
                # deep queues: the point is admission/deadline shedding,
                # not the per-doc QueueFull backstop masking it (same
                # depth for control and treatment — only admission
                # differs between the two servers)
                AUTOMERGE_TPU_SERVE_QUEUE_DEPTH="8192",
                # the operator contract: admission target wait tracks
                # the latency SLO. Proportional shedding then settles
                # the admitted queue near band center (2-4x target),
                # comfortably inside the client deadline.
                AUTOMERGE_TPU_ADMISSION_TARGET_WAIT_S=str(
                    ol_deadline_ms / 8.0 / 1000.0),
                # resample the load score often enough that a window
                # burst cannot slip past a stale-low cached score
                AUTOMERGE_TPU_ADMISSION_SAMPLE_S="0.01",
            )

            def ol_spawn(tmpdir, admission):
                env = dict(ol_env, AUTOMERGE_TPU_ADMISSION=admission)
                proc = subprocess.Popen(
                    [sys.executable, "-m", "automerge_tpu.rpc",
                     "--socket", "127.0.0.1:0", "--durable", tmpdir],
                    stderr=subprocess.PIPE, text=True, env=env,
                )
                port = int(re.search(
                    r"(\d+)\)", proc.stderr.readline()).group(1))
                threading.Thread(
                    target=lambda: [None for _ in proc.stderr],
                    daemon=True,
                ).start()
                return proc, port

            def ol_ask(sock, f, method, params):
                """Serial control-path request, retried through shed
                windows (openDurable is rank-1 and can itself be shed
                under full overload — a real client retries it)."""
                for _ in range(400):
                    sock.sendall((json.dumps(
                        {"id": 0, "method": method, "params": params})
                        + "\n").encode())
                    while True:
                        resp = json.loads(f.readline())
                        if resp.get("id") == 0:
                            break
                    if "error" not in resp:
                        return resp
                    time.sleep(0.025)
                return resp

            def ol_shutdown(proc, port):
                sock = socketmod.create_connection(("127.0.0.1", port))
                sock.sendall(b'{"id":1,"method":"shutdown"}\n')
                sock.makefile("r").readline()
                sock.close()
                proc.wait(timeout=60)

            def ol_server_stats(port):
                """Overload counters off the live server (metrics RPC):
                shed per class, deadline expiries per stage, brownout
                transitions, the queue-wait histogram."""
                sock = socketmod.create_connection(("127.0.0.1", port))
                f = sock.makefile("r")
                sock.sendall(
                    b'{"id":1,"method":"metrics",'
                    b'"params":{"format":"json"}}\n')
                snap = json.loads(f.readline())["result"]["metrics"]
                sock.close()
                out = {"shed": {}, "deadline_expired": {},
                       "brownout_transitions": {}}
                for it in snap:
                    name, labels = it.get("name"), it.get("labels", {})
                    if name == "serve.shed":
                        out["shed"][labels.get("class")] = it["value"]
                    elif name == "serve.deadline_expired":
                        out["deadline_expired"][
                            labels.get("stage")] = it["value"]
                    elif name == "cluster.brownout_transitions":
                        out["brownout_transitions"][
                            labels.get("to")] = it["value"]
                    elif name == "serve.load_score":
                        out["load_score"] = round(it["value"], 3)
                    elif name == "serve.queue_wait":
                        out["queue_wait_p95_s"] = round(
                            it.get("p95", 0.0), 6)
                return out

            class _OlStats:
                __slots__ = ("goodput", "late", "shed", "other",
                             "lats", "acked_keys", "done")

                def __init__(self):
                    self.goodput = 0  # success within its own deadline
                    self.late = 0  # success past the deadline
                    self.shed = 0  # DeadlineExceeded/Overloaded/Backpressure
                    self.other = 0
                    self.lats = []  # accepted-request latencies
                    self.acked_keys = []  # put keys covered by acked commit
                    self.done = False

            _SHED_TYPES = {"DeadlineExceeded", "Overloaded", "Backpressure"}

            def ol_client(port, doc_name, tag, n_ops, deadline_ms, window,
                          stats):
                """One driver: pipelined requests under an AIMD
                in-flight window (halve on shed, grow on success),
                7 puts then a commit, each stamped with its own deadline
                when ``deadline_ms`` is set. Ends with an undeadlined
                flush commit so every acked put is commit-covered for
                the readback audit."""
                sock = socketmod.create_connection(("127.0.0.1", port))
                sock.setsockopt(socketmod.IPPROTO_TCP,
                                socketmod.TCP_NODELAY, 1)
                sock.settimeout(120.0)
                f = sock.makefile("r")
                r = ol_ask(sock, f, "openDurable", {"name": doc_name})
                dh = r["result"]["doc"]
                sent = {}  # id -> (t_send, kind, key)
                acked_puts = {}  # id -> key (awaiting a covering commit)
                nid = [0]
                cwnd = [16.0]
                last_cut = [0.0]

                def send_one(i):
                    nid[0] += 1
                    if i % 8 == 7:
                        req = {"id": nid[0], "method": "commit",
                               "params": {"doc": dh}}
                        kind, key = "commit", None
                    else:
                        key = f"{tag}_{i:06}"
                        req = {"id": nid[0], "method": "put",
                               "params": {"doc": dh, "obj": "_root",
                                          "prop": key, "value": i}}
                        kind = "put"
                    if deadline_ms:
                        req["deadlineMs"] = deadline_ms
                    sent[nid[0]] = (time.perf_counter(), kind, key)
                    sock.sendall((json.dumps(req) + "\n").encode())

                def read_one():
                    resp = json.loads(f.readline())
                    rid = resp.get("id")
                    t0, kind, key = sent.pop(rid)
                    lat = time.perf_counter() - t0
                    if "error" in resp:
                        etype = resp["error"].get("type")
                        if etype in _SHED_TYPES:
                            stats.shed += 1
                            nw = time.perf_counter()
                            if nw - last_cut[0] > 0.1:
                                cwnd[0] = max(8.0, cwnd[0] * 0.6)
                                last_cut[0] = nw
                        else:
                            stats.other += 1
                        return
                    cwnd[0] = min(float(window), cwnd[0] + 0.5)
                    stats.lats.append(lat)
                    if deadline_ms and lat > deadline_ms / 1000.0:
                        stats.late += 1
                    else:
                        stats.goodput += 1
                    if kind == "put":
                        acked_puts[rid] = key
                    else:  # an acked commit covers every earlier ack
                        for pid in [p for p in acked_puts if p < rid]:
                            stats.acked_keys.append(acked_puts.pop(pid))

                i = 0
                while i < n_ops or sent:
                    while i < n_ops and len(sent) < min(window,
                                                        int(cwnd[0])):
                        send_one(i)
                        i += 1
                    if sent:
                        read_one()
                # flush: one undeadlined commit (retried through shed
                # windows) so surviving acked puts are commit-covered
                resp = ol_ask(sock, f, "commit", {"doc": dh})
                if "error" not in resp:
                    stats.acked_keys.extend(acked_puts.values())
                    acked_puts.clear()
                sock.close()
                stats.done = True

            def ol_drive(port, phase, n_ops, deadline_ms, window):
                """One phase: one client thread per doc against a
                phase-specific document set; returns (stats list, wall
                seconds, all joined)."""
                stats = []
                ts = []
                barrier = threading.Barrier(ol_docs + 1)

                def run(st, dname, tag):
                    barrier.wait()
                    ol_client(port, dname, tag, n_ops, deadline_ms,
                              window, st)

                for d in range(ol_docs):
                    st = _OlStats()
                    stats.append(st)
                    ts.append(threading.Thread(
                        target=run,
                        args=(st, f"{phase}{d}", f"{phase}_d{d}"),
                        daemon=True))
                for t in ts:
                    t.start()
                barrier.wait()
                t0 = time.perf_counter()
                for t in ts:
                    t.join(timeout=300.0)
                dt = time.perf_counter() - t0
                return stats, dt, all(st.done for st in stats)

            def ol_readback(port, phase):
                """{doc name: set of present keys} straight off the
                server — the acked-write-loss audit's ground truth."""
                sock = socketmod.create_connection(("127.0.0.1", port))
                f = sock.makefile("r")
                present = {}
                for d in range(ol_docs):
                    name = f"{phase}{d}"
                    r = ol_ask(sock, f, "openDurable", {"name": name})
                    dh = r["result"]["doc"]
                    r = ol_ask(sock, f, "keys", {"doc": dh,
                                                 "obj": "_root"})
                    present[name] = set(r["result"])
                sock.close()
                return present

            def ol_phase_summary(stats, dt):
                offered = sum(
                    st.goodput + st.late + st.shed + st.other
                    for st in stats)
                goodput = sum(st.goodput for st in stats)
                shed = sum(st.shed for st in stats)
                return {
                    "offered": offered,
                    "goodput": goodput,
                    "goodput_rps": round(goodput / dt, 1),
                    "late": sum(st.late for st in stats),
                    "shed": shed,
                    "shed_rate": round(shed / max(offered, 1), 4),
                    "errors_other": sum(st.other for st in stats),
                    "seconds": round(dt, 3),
                }

            tmp_ctl = tempfile.mkdtemp(prefix="amtpu_bench_ol_ctl_")
            tmp_un = tempfile.mkdtemp(prefix="amtpu_bench_ol_un_")
            ctl_proc = un_proc = None
            try:
                # -- controlled server: capacity, then overdrive --------
                ctl_proc, ctl_port = ol_spawn(tmp_ctl, "1")
                ol_drive(ctl_port, "wm", 256, 0, ol_cap_window)
                cap_stats, cap_dt, cap_ok = ol_drive(
                    ctl_port, "cap", ol_cap_ops, 0, ol_cap_window)
                capacity_rps = sum(
                    st.goodput for st in cap_stats) / cap_dt
                od_stats, od_dt, od_ok = ol_drive(
                    ctl_port, "od", ol_ops, ol_deadline_ms, ol_window)
                # zero acked-write loss: every put acked AND covered by
                # an acked commit must be present at readback
                acked = {f"od{d}": set() for d in range(ol_docs)}
                for st in od_stats:
                    for k in st.acked_keys:
                        acked[f"od{k.split('_d', 1)[1].split('_', 1)[0]}"
                              ].add(k)
                present = ol_readback(ctl_port, "od")
                lost = {
                    d: sorted(acked[d] - present[d])[:5]
                    for d in acked if acked[d] - present[d]
                }
                server_stats = ol_server_stats(ctl_port)
                ol_shutdown(ctl_proc, ctl_port)

                # -- control server: same overdrive, admission off ------
                un_proc, un_port = ol_spawn(tmp_un, "0")
                ol_drive(un_port, "wm", 256, 0, ol_cap_window)
                un_od_stats, un_od_dt, un_ok = ol_drive(
                    un_port, "od", ol_ops, ol_deadline_ms, ol_window)
                ol_shutdown(un_proc, un_port)
            finally:
                for p_ in (ctl_proc, un_proc):
                    if p_ is not None and p_.poll() is None:
                        p_.kill()
                        p_.wait(timeout=10)
                shutil.rmtree(tmp_ctl, ignore_errors=True)
                shutil.rmtree(tmp_un, ignore_errors=True)

            od = ol_phase_summary(od_stats, od_dt)
            un = ol_phase_summary(un_od_stats, un_od_dt)
            ol_cfg = {
                "docs": ol_docs,
                "overdrive": ol_overdrive,
                "ops_per_client": ol_ops,
                "window": ol_window,
                "cap_window": ol_cap_window,
                "deadline_ms": ol_deadline_ms,
                "capacity_rps": round(capacity_rps, 1),
                **od,
                "goodput_ratio": round(
                    od["goodput_rps"] / max(capacity_rps, 1e-9), 3),
                "acked_write_loss": sum(len(v) for v in lost.values()),
                "lost_sample": lost,
                "deadlocked": not (cap_ok and od_ok and un_ok),
                "server": server_stats,
                **_latency_percentiles(
                    "bench.overload.accepted_latency",
                    [x for st in od_stats for x in st.lats]),
                "control": {
                    **un,
                    "goodput_ratio": round(
                        un["goodput_rps"] / max(capacity_rps, 1e-9), 3),
                    **_latency_percentiles(
                        "bench.overload.control_latency",
                        [x for st in un_od_stats for x in st.lats]),
                },
            }
    except Exception as e:  # noqa: BLE001 — degrade, record, continue
        import traceback

        tb = traceback.format_exc()
        ol_cfg = {"overload_error": repr(e)[:500]}
        print(f"overload config failed:\n{tb}", file=sys.stderr, flush=True)
    results["overload"] = ol_cfg
    note(f"overload: {results['overload']}")
    wall_mark("overload")

    # ---- config: persistence (run-coded snapshot codec vs legacy chunk) ----
    # One column format from disk to device. A: cold-open latency of the
    # SAME document persisted as a run-coded ARSN image (the default
    # writer) vs the legacy chunk codec (AUTOMERGE_TPU_RUNSNAP=0) —
    # percentiles over repeated from-disk opens, plus hydrate-to-first-
    # read (open + first value read) for each codec. The zero-re-encode
    # contract rides along: a device-mirror build after a run-coded open
    # must not advance oplog.hydrate_reencode, and the chunk path MUST
    # (non-vacuous counter). B: compaction write amplification — the
    # cost-gated compactor (compact_cost_ratio: defer while the journal
    # tail is cheaper than the image rewrite) vs full-rewrite-at-every-
    # threshold, in snapshot bytes written per committed op.
    ps_cfg = {}
    try:
        if env_flag("BENCH_PERSISTENCE", "1") != "0":
            import shutil
            import tempfile

            from automerge_tpu.storage.durable import SNAPSHOT_NAME

            ps_ops = env_int("BENCH_PS_OPS", 100_000)
            ps_opens = env_int("BENCH_PS_OPENS", 12)
            ps_commits = env_int("BENCH_PS_COMMITS", 600)
            ps_every = env_int("BENCH_PS_COMPACT_EVERY", 48)
            ps_ratio = float(env_flag("BENCH_PS_COST_RATIO", "4.0"))

            ps_dir = tempfile.mkdtemp(prefix="amtpu_bench_ps_")
            try:
                run_path = os.path.join(ps_dir, "run")
                chunk_path = os.path.join(ps_dir, "chunk")
                dd = AutoDoc.open(
                    run_path, fsync="never",
                    actor=ActorId(bytes([21]) * 16),
                )
                tob = dd.put_object("_root", "text", ObjType.TEXT)
                dd.put("_root", "probe", 1)
                dd.commit()
                edits = trace[:ps_ops]
                step = max(1, min(2000, max(1, len(edits) // 64)))
                for lo in range(0, len(edits), step):
                    W.apply_edits(dd, tob, edits[lo:lo + step])
                    dd.commit()
                dd.compact()
                heads_a = sorted(dd.get_heads())
                dd.close()

                # the SAME document re-persisted through the legacy chunk
                # writer: copy the doc dir, rewrite its snapshot with the
                # run-coded writer disabled
                shutil.copytree(run_path, chunk_path)
                prior = os.environ.get("AUTOMERGE_TPU_RUNSNAP")
                os.environ["AUTOMERGE_TPU_RUNSNAP"] = "0"
                try:
                    d2 = AutoDoc.open(chunk_path, fsync="never")
                    assert d2.compact(), "legacy snapshot rewrite refused"
                    heads_b = sorted(d2.get_heads())
                    d2.close()
                finally:
                    if prior is None:
                        os.environ.pop("AUTOMERGE_TPU_RUNSNAP", None)
                    else:
                        os.environ["AUTOMERGE_TPU_RUNSNAP"] = prior

                def cold_open_stats(path, hist_name):
                    """Repeated from-disk opens of a fully-compacted doc:
                    per-open latency, open+first-read, the re-encode
                    counter across one device-mirror build, and the
                    hydrate byte counters by codec label."""
                    lats = []
                    first_read = None
                    re0 = T.counters.get("oplog.hydrate_reencode", 0)
                    hb0 = dict(obs.counter_values(
                        "store.hydrate_bytes", "codec"))
                    for i in range(ps_opens):
                        t0 = time.perf_counter()
                        d_ = AutoDoc.open(path, fsync="never")
                        t_open = time.perf_counter() - t0
                        v = d_.get("_root", "probe")
                        t_read = time.perf_counter() - t0
                        assert v is not None, v
                        lats.append(t_open)
                        if first_read is None:
                            first_read = t_read
                            # cold -> hot: the device mirror must source
                            # the retained run image (legacy: re-extract,
                            # which the counter charges)
                            d_.build_device_mirror()
                        d_.close()
                    hb1 = dict(obs.counter_values(
                        "store.hydrate_bytes", "codec"))
                    return {
                        "snapshot_bytes": os.path.getsize(
                            os.path.join(path, SNAPSHOT_NAME)),
                        "hydrate_to_first_read_s": round(first_read, 4),
                        "hydrate_reencode": T.counters.get(
                            "oplog.hydrate_reencode", 0) - re0,
                        "hydrate_bytes": {
                            k: hb1.get(k, 0) - hb0.get(k, 0)
                            for k in hb1
                            if hb1.get(k, 0) != hb0.get(k, 0)
                        },
                        **_latency_percentiles(hist_name, lats),
                    }

                rs = cold_open_stats(
                    run_path, "bench.persistence.cold_open_runsnap")
                cs = cold_open_stats(
                    chunk_path, "bench.persistence.cold_open_chunk")

                def write_amp(tag, cost_ratio):
                    """ps_commits small commits against aggressive
                    compaction thresholds; the bytes the compactor
                    rewrote per committed op is the write-amp figure."""
                    b0 = T.counters.get("compact.bytes_written", 0)
                    r0 = T.counters.get("compact.runs", 0)
                    d_ = AutoDoc.open(
                        os.path.join(ps_dir, f"wa_{tag}"), fsync="never",
                        compact_max_records=ps_every,
                        compact_max_bytes=1 << 30,
                        compact_cost_ratio=cost_ratio,
                        actor=ActorId(bytes([22]) * 16),
                    )
                    pay = "v" * 160
                    t0 = time.perf_counter()
                    for i in range(ps_commits):
                        d_.put("_root", f"k{i % 256:04}", f"{pay}{i}")
                        d_.commit()
                    dt = time.perf_counter() - t0
                    d_.close()
                    written = T.counters.get(
                        "compact.bytes_written", 0) - b0
                    return {
                        "cost_ratio": cost_ratio,
                        "compactions": T.counters.get(
                            "compact.runs", 0) - r0,
                        "snapshot_bytes_written": written,
                        "bytes_per_op": round(written / ps_commits, 1),
                        "commits_per_sec": round(ps_commits / dt, 1),
                    }

                wa_full = write_amp("full", 0.0)
                wa_gated = write_amp("gated", ps_ratio)

                ps_cfg = {
                    "edits": len(edits),
                    "opens": ps_opens,
                    "commits": ps_commits,
                    "heads_identical": heads_a == heads_b,
                    "runsnap": rs,
                    "chunk": cs,
                    "cold_open_p50_speedup": round(
                        cs["latency_p50_s"] / max(rs["latency_p50_s"],
                                                  1e-9), 2),
                    "cold_open_p99_speedup": round(
                        cs["latency_p99_s"] / max(rs["latency_p99_s"],
                                                  1e-9), 2),
                    "full_rewrite": wa_full,
                    "cost_gated": wa_gated,
                    "write_amp_reduction": round(
                        wa_full["bytes_per_op"]
                        / max(wa_gated["bytes_per_op"], 1e-9), 2),
                }
            finally:
                shutil.rmtree(ps_dir, ignore_errors=True)
    except Exception as e:  # noqa: BLE001 — degrade, record, continue
        import traceback

        tb = traceback.format_exc()
        ps_cfg = {"persistence_error": repr(e)[:500]}
        print(f"persistence config failed:\n{tb}", file=sys.stderr,
              flush=True)
    results["persistence"] = ps_cfg
    note(f"persistence: {results['persistence']}")
    wall_mark("persistence")
    wall_s["total"] = round(sum(wall_s.values()), 3)

    out = {
        "metric": "edit_trace_fanin_merge_ops_per_sec",
        "value": results["fanin"]["ops_per_sec"],
        "unit": "ops/s",
        "vs_baseline": results["fanin"]["vs_baseline"],
        # provenance: which code produced these numbers, under exactly
        # which resolved knobs, on which box — the JSON is
        # self-describing across PRs and perf_gate can refuse to compare
        # points from different hosts
        "git_commit": git_commit(),
        "host": host_fingerprint(),
        "schema_version": BENCH_SCHEMA_VERSION,
        "config": dict(sorted(RESOLVED_CONFIG.items())),
        # memory trajectory alongside throughput: this process's peak
        # RSS over the whole run (ru_maxrss is KiB on Linux) — the
        # number the tiered-store work is accountable to across PRs
        "max_rss_bytes": _resource.getrusage(
            _resource.RUSAGE_SELF).ru_maxrss * 1024,
        "configs": results,
        # per-config wall clock + total: the additive cost view — a
        # config whose setup quietly doubles shows up here even when its
        # headline throughput number holds
        "wall_s": wall_s,
        # cumulative device-phase attribution across the whole run
        # (trace.time spans: device.extract / h2d / kernel / readback /
        # materialize, merge.host)
        "trace_timings": T.timing_summary(),
        # every kernel dispatch over the whole run, by dispatch path
        # (per_doc / batched / sharded — the device.kernel_launches
        # counter each dispatch site increments)
        "kernel_launches": obs.counter_values(
            "device.kernel_launches", "path"
        ),
        # run-native demotions over the whole run: which columns shipped
        # dense anyway and why (ratio = run table degenerate past the
        # gate, dtype = not int32/bool, short = below the run-encode
        # floor) — the per-column view of the ratio-gate dense fallback
        "run_native_fallback": {
            "by_reason": obs.counter_values(
                "device.run_native_fallback", "reason"
            ),
            "by_column": obs.counter_values(
                "device.run_native_fallback", "column"
            ),
        },
        # pack-site occupancy across every batched launch of the run:
        # useful rows / (useful + padded) from the device.batch_rows /
        # device.batch_padding_rows counters (None = nothing packed)
        "batch_occupancy": (
            lambda u, p: round(u / (u + p), 4) if (u + p) else None
        )(
            obs.counter_values("device.batch_rows", "").get("", 0),
            obs.counter_values("device.batch_padding_rows", "").get("", 0),
        ),
        # per-change-hash extraction-cache efficacy across the whole run:
        # the observatory names extract as a dominant host stage, and
        # this is the knob that decides how much of it is re-decode
        # (hits/misses from extract.change_cache_hit/miss; None = the
        # cache was never consulted)
        "extract_cache": (
            lambda h, ms: {
                "hits": h,
                "misses": ms,
                "cache_hit_ratio": (
                    round(h / (h + ms), 4) if (h + ms) else None
                ),
            }
        )(
            obs.counter_values("extract.change_cache_hit", "").get("", 0),
            obs.counter_values("extract.change_cache_miss", "").get("", 0),
        ),
        # span-ring health: how much of the run the flight recorder /
        # Perfetto export can still see (dropped > 0 means the ring
        # wrapped and the phase trace is a suffix, not the whole run)
        "span_buffer": {
            "recorded": len(obs.recorder),
            "dropped": obs.counter_values(
                "obs.spans_dropped", "").get("", 0),
        },
        # tail attribution: per-phase latency distributions from the span
        # histograms (log-bucketed; "what is p99 merge latency")
        "phase_percentiles": {
            e["name"] + "".join(
                "{%s=%s}" % (k, v) for k, v in sorted(e["labels"].items())
            ): {k: round(e[k], 6) for k in ("p50", "p95", "p99")}
            for e in obs.snapshot()
            if e["type"] == "histogram"
            and e["name"].startswith(("device.", "merge.", "journal.",
                                      "sync.", "compact.", "rpc.",
                                      "group_commit."))
        },
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
