#!/usr/bin/env python
"""Benchmark: edit-trace N-way fan-in merge, device kernel vs host apply.

The north-star workload (BASELINE.json): K divergent replicas of a text
document built from the canonical edit trace (reference:
rust/edit-trace/edits.json, 259,778 real editing operations) merged into
one converged document. The device path resolves the whole merged op log
in one batched kernel (automerge_tpu/ops/merge.py); the baseline is the
host-side sequential apply loop (automerge_tpu/core), the same algorithm
shape as the reference's ``apply_changes``.

Prints ONE JSON line:
  {"metric": ..., "value": ops/sec through the device merge,
   "unit": "ops/s", "vs_baseline": speedup over host sequential merge}
"""

import json
import os
import sys
import time

import numpy as np

TRACE = "/root/reference/rust/edit-trace/edits.json"

BASE_EDITS = int(os.environ.get("BENCH_BASE_EDITS", "8000"))
FORKS = int(os.environ.get("BENCH_FORKS", "64"))
FORK_EDITS = int(os.environ.get("BENCH_FORK_EDITS", "150"))
REPS = int(os.environ.get("BENCH_REPS", "3"))


def load_trace():
    if os.path.exists(TRACE):
        with open(TRACE) as f:
            return json.load(f)
    # synthetic fallback: same shape as the trace, deterministic
    rng = np.random.default_rng(0)
    edits, length = [], 0
    for _ in range(BASE_EDITS + FORKS * FORK_EDITS + 1000):
        if length == 0 or rng.random() < 0.85:
            pos = int(rng.integers(0, length + 1))
            edits.append([pos, 0, "x"])
            length += 1
        else:
            pos = int(rng.integers(0, length))
            edits.append([pos, 1])
            length -= 1
    return edits


def apply_edits(doc, text_obj, edits):
    for e in edits:
        ln = doc.length(text_obj)
        pos = min(e[0], ln)
        ndel = min(e[1], ln - pos)
        doc.splice_text(text_obj, pos, ndel, "".join(e[2:]))


def main():
    from automerge_tpu.api import AutoDoc
    from automerge_tpu.ops import DeviceDoc, OpLog
    from automerge_tpu.ops.merge import merge_kernel
    from automerge_tpu.types import ActorId, ObjType

    trace = load_trace()
    t0 = time.perf_counter()
    base = AutoDoc(actor=ActorId(bytes([1]) * 16))
    text = base.put_object("_root", "text", ObjType.TEXT)
    apply_edits(base, text, trace[:BASE_EDITS])
    base.commit()
    t_base = time.perf_counter() - t0

    forks = []
    t0 = time.perf_counter()
    for i in range(FORKS):
        f = base.fork(actor=ActorId(bytes([2]) * 15 + bytes([i])))
        lo = BASE_EDITS + i * FORK_EDITS
        apply_edits(f, text, trace[lo : lo + FORK_EDITS])
        f.commit()
        forks.append(f)
    t_forks = time.perf_counter() - t0

    # --- device path -------------------------------------------------------
    import jax
    import jax.numpy as jnp

    t0 = time.perf_counter()
    log = OpLog.from_documents(forks)
    t_extract = time.perf_counter() - t0
    cols = {k: jnp.asarray(v) for k, v in log.padded_columns().items()}
    jax.block_until_ready(cols)
    # warmup / compile
    jax.block_until_ready(merge_kernel(cols))
    t_kernel = min(
        _timed(lambda: jax.block_until_ready(merge_kernel(cols)))
        for _ in range(REPS)
    )

    # --- host baseline: sequential merge of the same replicas --------------
    t0 = time.perf_counter()
    host = AutoDoc(actor=ActorId(bytes([3]) * 16))
    for f in forks:
        host.merge(f)
    t_host = time.perf_counter() - t0

    # sanity: converged state must match
    dev = DeviceDoc(log, {k: np.asarray(v) for k, v in merge_kernel(cols).items()})
    assert dev.text(text) == host.text(text), "device/host merge divergence"

    ops = log.n
    dev_rate = ops / t_kernel
    host_rate = ops / t_host
    result = {
        "metric": "edit_trace_fanin_merge_ops_per_sec",
        "value": round(dev_rate, 1),
        "unit": "ops/s",
        "vs_baseline": round(dev_rate / host_rate, 2),
    }
    print(json.dumps(result))
    if os.environ.get("BENCH_VERBOSE"):
        print(
            json.dumps(
                {
                    "ops_merged": ops,
                    "forks": FORKS,
                    "capacity": int(cols["action"].shape[0]),
                    "t_kernel_s": round(t_kernel, 4),
                    "t_host_merge_s": round(t_host, 3),
                    "t_extract_s": round(t_extract, 3),
                    "t_base_build_s": round(t_base, 3),
                    "t_fork_build_s": round(t_forks, 3),
                    "host_ops_per_sec": round(host_rate, 1),
                    "device": str(jax.devices()[0]),
                },
            ),
            file=sys.stderr,
        )


def _timed(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


if __name__ == "__main__":
    main()
