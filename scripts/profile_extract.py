#!/usr/bin/env python
"""Profile OpLog.from_changes on the fan-in workload (the round-4 target)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import cProfile
import pstats

from automerge_tpu import bench as W
from automerge_tpu.ops import OpLog

trace = W.load_trace()
base_edits = int(os.environ.get("BENCH_BASE_EDITS", 120_000))
n_replicas = int(os.environ.get("BENCH_REPLICAS", 1024))
fork_edits = int(os.environ.get("BENCH_FORK_EDITS", 250))
t0 = time.perf_counter()
base = W.build_base(trace, base_edits)
print(f"base build: {time.perf_counter()-t0:.2f}s", file=sys.stderr)
t0 = time.perf_counter()
replica_changes = W.synth_fanin(base, trace, n_replicas, fork_edits, base_edits)
changes = list(base.changes) + replica_changes
print(f"synth: {time.perf_counter()-t0:.2f}s", file=sys.stderr)

# warm
log = OpLog.from_changes(changes)
print(f"n={log.n}", file=sys.stderr)

for _ in range(3):
    t0 = time.perf_counter()
    log = OpLog.from_changes(changes)
    print(f"from_changes: {time.perf_counter()-t0:.4f}s", file=sys.stderr)

if os.environ.get("PROFILE", "1") != "0":
    pr = cProfile.Profile()
    pr.enable()
    log = OpLog.from_changes(changes)
    pr.disable()
    stats = pstats.Stats(pr, stream=sys.stderr)
    stats.sort_stats("cumulative").print_stats(30)
