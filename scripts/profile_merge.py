#!/usr/bin/env python
"""Profile the fan-in merge half: log.columns() prep + native merge engine."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from automerge_tpu import bench as W
from automerge_tpu import native
from automerge_tpu.ops import DeviceDoc, OpLog
from automerge_tpu.ops.merge import merge_columns

trace = W.load_trace()
base_edits = int(os.environ.get("BENCH_BASE_EDITS", 259_778))
n_replicas = int(os.environ.get("BENCH_REPLICAS", 1024))
fork_edits = int(os.environ.get("BENCH_FORK_EDITS", 250))
t0 = time.perf_counter()
base = W.build_base(trace, base_edits)
print(f"base build: {time.perf_counter()-t0:.2f}s", file=sys.stderr)
t0 = time.perf_counter()
replica_changes = W.synth_fanin(base, trace, n_replicas, fork_edits, base_edits)
changes = list(base.changes) + replica_changes
print(f"synth: {time.perf_counter()-t0:.2f}s", file=sys.stderr)

log = OpLog.from_changes(changes)
kw = dict(fetch=DeviceDoc.READ_FETCH, n_objs=log.n_objs, n_props=len(log.props))
merge_columns(log.columns(), **kw)  # warm

for _ in range(4):
    log = OpLog.from_changes(changes)
    t0 = time.perf_counter()
    cols = log.columns()
    t_cols = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = native.merge_cols(cols, log.n_objs, want_elem_index=True)
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    merge_columns(log.columns(), **kw)
    t_full = time.perf_counter() - t0
    print(
        f"columns() {t_cols*1e3:.1f}ms  native.merge_cols {t_native*1e3:.1f}ms"
        f"  merge_columns e2e {t_full*1e3:.1f}ms",
        file=sys.stderr,
    )

if os.environ.get("PROFILE", "0") != "0":
    import cProfile
    import pstats

    log = OpLog.from_changes(changes)
    pr = cProfile.Profile()
    pr.enable()
    merge_columns(log.columns(), **kw)
    pr.disable()
    pstats.Stats(pr, stream=sys.stderr).sort_stats("cumulative").print_stats(25)
